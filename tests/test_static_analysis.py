"""scanner-check static-analysis suite tests.

Three layers:
  * fixture snippets per pass family — known-bad code must produce the
    expected finding codes, the clean twin must produce none (the
    analyzer's own regression suite);
  * suppression/baseline round-trip — inline pragmas, baseline
    fingerprint stability, mandatory justifications, stale detection;
  * the tier-1 GATE — the analyzer over the whole scanner_tpu package
    must report zero unsuppressed findings (the repo stays lint-clean
    the same way it stays test-green).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from scanner_tpu.analysis.static import (BaselineError, all_passes,
                                         analyze, load_baseline,
                                         run_analysis, split_findings,
                                         write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _analyze(root, *relfiles):
    return analyze([os.path.join(root, f) for f in relfiles]
                   if relfiles else [str(root)], root=str(root))


def _codes(findings):
    return sorted(f.code for f in findings)


def _write(root, rel, src):
    path = os.path.join(str(root), rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(src))
    return path


# ---------------------------------------------------------------------------
# pass framework basics
# ---------------------------------------------------------------------------

def test_codes_are_unique_and_documented():
    seen = {}
    for p in all_passes():
        assert p.name
        for code, desc in p.codes.items():
            assert code.startswith("SC") and desc
            assert code not in seen, f"{code} claimed by two passes"
            seen[code] = p.name
    assert len(seen) >= 15


def test_syntax_error_is_a_finding(tmp_path):
    _write(tmp_path, "broken.py", "def f(:\n")
    proj, findings = _analyze(tmp_path)
    assert [f.code for f in proj.parse_errors] == ["SC001"]


# ---------------------------------------------------------------------------
# family 1: tracer safety
# ---------------------------------------------------------------------------

TRACER_BAD = """
    import time
    import random
    import numpy as np
    import jax
    import jax.numpy as jnp

    _CACHE = {}

    def poke(v):
        _CACHE["k"] = v

    @jax.jit
    def kern(x):
        if x > 0:                     # SC102
            y = np.sum(x)             # SC101
        else:
            y = jnp.sum(x)
        t = time.time()               # SC103
        r = np.random.rand(3)         # SC103
        s = _CACHE.get("scale", 1.0)  # SC104
        return y * t * s + r.sum()

    _jf = jax.jit(kern)

    def call(frames, k):
        return _jf(frames[:k])        # SC105
"""

TRACER_CLEAN = """
    import functools
    import numpy as np
    import jax
    import jax.numpy as jnp

    TABLE = {"a": 1}   # never mutated from a function: fine to capture

    @functools.partial(jax.jit, static_argnames=("bins",))
    def kern(x, bins):
        if bins > 2:                  # static arg: fine
            return jnp.sum(x)
        if x.ndim == 3:               # shape access: static, fine
            return x.mean()
        h = np.zeros(4)               # numpy on constants: fine
        return x + h[0] + TABLE["a"]

    def host_path(x):
        return np.sum(x)              # not jitted: numpy is fine

    _jf = jax.jit(kern)

    def call(frames):
        return _jf(frames, 4)         # full batch, no ragged slice
"""


def test_tracer_bad_fixture(tmp_path):
    _write(tmp_path, "bad.py", TRACER_BAD)
    _, findings = _analyze(tmp_path)
    counts = {c: _codes(findings).count(c) for c in set(_codes(findings))}
    assert counts.get("SC101") == 1
    assert counts.get("SC102") == 1
    assert counts.get("SC103") == 2
    assert counts.get("SC104") == 1
    assert counts.get("SC105") == 1


def test_tracer_clean_fixture(tmp_path):
    _write(tmp_path, "clean.py", TRACER_CLEAN)
    _, findings = _analyze(tmp_path)
    assert not [f for f in findings if f.code.startswith("SC1")], \
        [f.format() for f in findings]


# SC106 scope is engine/kernels code: the same snippet is bad inside an
# engine/ directory and invisible outside it (host tooling may pin chips)
AFFINITY_BAD = """
    import jax
    from jax import local_devices

    def stage(x):
        d = jax.devices()[0]              # SC106: fixed-chip pin
        return jax.device_put(x), d       # SC106: bare device_put

    def probe():
        return local_devices()[0]         # SC106: aliased import pin
"""

AFFINITY_CLEAN = """
    import jax

    def stage(x, device):
        # explicit (possibly-None) device: placement decided upstream
        return jax.device_put(x, device)

    def probe():
        return jax.default_backend() == "tpu"   # platform probe, no pin

    def enumerate_chips():
        return list(jax.local_devices())        # whole list: no pin
"""


def test_affinity_bad_fixture_in_engine_scope(tmp_path):
    _write(tmp_path, "engine/bad_dev.py", AFFINITY_BAD)
    _, findings = _analyze(tmp_path)
    assert _codes(findings).count("SC106") == 3, \
        [f.format() for f in findings]


def test_affinity_clean_fixture_and_scope(tmp_path):
    _write(tmp_path, "kernels/clean_dev.py", AFFINITY_CLEAN)
    # identical bad code OUTSIDE engine/kernels scope: not SC106's beat
    _write(tmp_path, "tools/pinner.py", AFFINITY_BAD)
    _, findings = _analyze(tmp_path)
    assert "SC106" not in _codes(findings), \
        [f.format() for f in findings]


def test_tracer_scan_body_and_kernel_execute(tmp_path):
    _write(tmp_path, "scanny.py", """
        import time
        import jax

        def body(carry, x):
            t = time.time()           # SC103: scan body is traced
            return carry + x * t, x

        def drive(xs, ev):
            import jax.numpy as jnp
            out = jax.lax.scan(body, jnp.zeros(()), xs)
            return out, ev.kernel.execute(xs)   # SC105: raw execute()
    """)
    _, findings = _analyze(tmp_path)
    assert "SC103" in _codes(findings)
    assert "SC105" in _codes(findings)


# ---------------------------------------------------------------------------
# family 2: concurrency
# ---------------------------------------------------------------------------

CONC_BAD = """
    import threading
    import time

    class Svc:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()
            self.n = 0

        def ab(self):
            with self.a:
                with self.b:          # SC201 (vs ba)
                    self.n = 1

        def ba(self):
            with self.b:
                with self.a:
                    return self.n

        def reenter(self):
            with self.a:
                self.ab()             # SC201 self-deadlock

        def slow(self):
            with self.a:
                time.sleep(0.5)       # SC202

        def bare(self):
            self.n = 2                # SC203
"""

CONC_CLEAN = """
    import threading
    import queue

    class Svc:
        def __init__(self):
            self.a = threading.RLock()
            self.b = threading.Lock()
            self.n = 0
            self.q = queue.Queue()

        def ab(self):
            with self.a:
                with self.b:
                    self.n = 1

        def ab2(self):
            with self.a:              # same order: fine
                with self.b:
                    return self.n

        def reenter(self):
            with self.a:
                self.ab()             # RLock: reentry is fine

        def bounded(self):
            with self.b:
                return self.q.get(timeout=0.25)   # bounded: fine

        def read_only(self):
            return self.n             # read, not write: fine
"""


def test_concurrency_bad_fixture(tmp_path):
    _write(tmp_path, "svc.py", CONC_BAD)
    _, findings = _analyze(tmp_path)
    codes = _codes(findings)
    assert codes.count("SC201") == 2   # ABBA + self-deadlock
    assert "SC202" in codes
    assert "SC203" in codes


def test_concurrency_clean_fixture(tmp_path):
    _write(tmp_path, "svc.py", CONC_CLEAN)
    _, findings = _analyze(tmp_path)
    assert not [f for f in findings if f.code.startswith("SC2")], \
        [f.format() for f in findings]


# ---------------------------------------------------------------------------
# family 3: contracts (synthetic mini-repo)
# ---------------------------------------------------------------------------

def _contract_repo(tmp_path):
    _write(tmp_path, "setup.py", "# root marker\n")
    _write(tmp_path, "docs/observability.md", """
        | `scanner_tpu_good_total` | counter | documented |
        | `scanner_tpu_ghost_total` | counter | documented but unregistered |
    """)
    _write(tmp_path, "docs/guide.md", """
        `SCANNER_TPU_DOCUMENTED` is a knob.  `[net] port` is config.
        The key `port` is documented here.
    """)
    _write(tmp_path, "pkg/config.py", """
        def default_config():
            return {"net": {"port": 1}}
    """)
    _write(tmp_path, "pkg/util/faults.py", """
        SITES = ("rpc.call", "storage.write")
        MODES = ("raise", "delay")
        _EXC = {"fault": lambda m: Exception(m)}
        NAMED_PLANS = {"p1": "rpc.call:raise", "p2": "nosuch.site:crash",
                       "p3": "rpc.call:explode:n=1",
                       "p4": "rpc.call:raise:exc=nosuchexc"}
        ACTIVE = False

        def inject(site, data=None, detail=""):
            return data
    """)
    _write(tmp_path, "pkg/m.py", """
        import os
        from .util import faults as _faults

        def registry():
            return None

        M_GOOD = registry().counter("scanner_tpu_good_total", "ok")
        M_UNDOC = registry().counter("scanner_tpu_undoc_total", "x")
        M_BAD = registry().counter("BadName", "x")
        M_NOTOT = registry().counter("scanner_tpu_rows", "x")
        M_NOHELP = registry().gauge("scanner_tpu_depth", "")

        def knobs(cfg):
            a = os.environ.get("SCANNER_TPU_DOCUMENTED")
            b = os.environ.get("SCANNER_TPU_SECRET")
            return a, b, cfg["net"]["port"], cfg["net"]["missing"]

        def hooks(data):
            data = _faults.inject("rpc.call", data)
            return _faults.inject("typo.site", data)

        class RpcServer:
            def __init__(self, name, methods, port=0):
                pass

        def serve(handler):
            return RpcServer("svc", {"Reg": handler})

        def client(c):
            return c.call("NotRegistered")
    """)
    return tmp_path


def test_contract_fixture_codes(tmp_path):
    _contract_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    by_code = {}
    for f in findings:
        by_code.setdefault(f.code, []).append(f)

    msgs = [f.message for f in by_code.get("SC301", [])]
    assert any("scanner_tpu_undoc_total" in m for m in msgs)
    assert any("scanner_tpu_ghost_total" in m for m in msgs)
    # name pattern + counter-_total + empty help
    assert len(by_code.get("SC302", [])) == 3
    msgs = [f.message for f in by_code.get("SC303", [])]
    assert any("SCANNER_TPU_SECRET" in m for m in msgs)
    assert not any("SCANNER_TPU_DOCUMENTED" in m for m in msgs)
    msgs = [f.message for f in by_code.get("SC304", [])]
    assert any("missing" in m for m in msgs)
    assert not any("[net] port" in m for m in msgs)
    msgs = [f.message for f in by_code.get("SC305", [])]
    assert any("typo.site" in m for m in msgs)          # unknown inject
    assert any("storage.write" in m for m in msgs)      # unwired site
    assert any("nosuch.site" in m for m in msgs)        # bad named plan
    assert any("unknown mode `explode`" in m for m in msgs)
    assert any("unknown exc `nosuchexc`" in m for m in msgs)
    # the valid clause shapes raise nothing extra
    assert not any("`raise`" in m for m in msgs)
    msgs = [f.message for f in by_code.get("SC306", [])]
    assert any("NotRegistered" in m for m in msgs)      # called, no server
    assert any("`Reg`" in m for m in msgs)              # registered, dead
    assert by_code.get("SC307"), "missing RPC_CONTRACTS must be flagged"


def _alert_repo(tmp_path, doc_rules=("rule_a", "rule_b"),
                code_rules=("rule_a", "rule_b"),
                cfg_keys=("enabled", "rules"),
                schema_keys=("enabled", "rules"),
                with_markers=True):
    """Synthetic mini-repo for the SC308 alert-rule contract lints."""
    _write(tmp_path, "setup.py", "# root marker\n")
    rows = "\n".join(f"| `{n}` | warning | something |"
                     for n in doc_rules)
    table = (f"<!-- default-alert-rules:begin -->\n"
             f"| Rule | Severity | Fires when |\n|---|---|---|\n"
             f"{rows}\n<!-- default-alert-rules:end -->\n"
             if with_markers else rows)
    _write(tmp_path, "docs/observability.md", f"""
        Default ruleset table:

        {table}

        The keys `enabled`, `rules` and `bogus` are documented so the
        SC304 lint stays quiet in this fixture.
    """)
    rules = ",\n            ".join(
        f'Rule(name="{n}", series="scanner_tpu_x")' for n in code_rules)
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/util/health.py", f"""
        def Rule(**kw):
            return kw

        CONFIG_KEYS = ({schema},)

        DEFAULT_RULES = (
            {rules},
        )
    """)
    cfg = ", ".join(f'"{k}": 1' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"alerts": {{{cfg}}}}}
    """)
    return tmp_path


def test_alert_contract_clean_fixture_is_quiet(tmp_path):
    _alert_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC308"] == []


def test_alert_contract_rule_names_both_directions(tmp_path):
    _alert_repo(tmp_path, doc_rules=("rule_a", "rule_ghost"),
                code_rules=("rule_a", "rule_undoc"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC308"]
    assert any("rule_undoc" in m and "missing from" in m for m in msgs)
    assert any("rule_ghost" in m and "no such rule" in m for m in msgs)
    assert not any("`rule_a`" in m for m in msgs)


def test_alert_contract_missing_marker_table(tmp_path):
    _alert_repo(tmp_path, with_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC308"]
    assert any("marker table" in m for m in msgs)


def test_alert_contract_config_schema_both_directions(tmp_path):
    _alert_repo(tmp_path, cfg_keys=("enabled", "rules", "bogus"),
                schema_keys=("enabled", "rules", "interval"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC308"]
    assert any("[alerts] bogus" in m and "does not accept" in m
               for m in msgs)
    assert any("`interval`" in m and "declares no" in m for m in msgs)
    assert not any("enabled" in m for m in msgs)


def _cost_repo(tmp_path, kernel_has_cost=True,
               declared=("scanner_tpu_eff_a", "scanner_tpu_eff_b"),
               registered=("scanner_tpu_eff_a", "scanner_tpu_eff_b"),
               doc_series=("scanner_tpu_eff_a", "scanner_tpu_eff_b"),
               with_markers=True):
    """Synthetic mini-repo for the SC309 cost-model contract lints."""
    _write(tmp_path, "setup.py", "# root marker\n")
    cost = ("\n            def cost(self, shapes):\n"
            "                return None\n" if kernel_has_cost else "\n")
    _write(tmp_path, "pkg/kernels/imgk.py", f"""
        from pkg.common import DeviceType
        from pkg.graph.ops import Kernel, register_op

        @register_op(device=DeviceType.TPU, batch=4)
        class DeviceK(Kernel):
            def execute(self, frame):
                return frame
{cost}
        @register_op()
        class HostK(Kernel):
            def execute(self, frame):
                return frame
    """)
    regs = "\n        ".join(
        f'_G{i} = _mx.registry().gauge("{n}", "help text", '
        f'labels=["op"])' for i, n in enumerate(registered))
    decl = ", ".join(f'"{n}"' for n in declared)
    _write(tmp_path, "pkg/util/coststats.py", f"""
        from . import metrics as _mx

        {regs}

        EFFICIENCY_SERIES = ({decl},)
    """)
    rows = "\n".join(f"| `{n}` | gauge | x |" for n in doc_series)
    table = (f"<!-- efficiency-series:begin -->\n"
             f"| Series | Type | Meaning |\n|---|---|---|\n"
             f"{rows}\n<!-- efficiency-series:end -->\n"
             if with_markers else rows)
    all_series = sorted(set(declared) | set(registered) | set(doc_series))
    _write(tmp_path, "docs/observability.md", f"""
        Catalog (every fixture series mentioned so SC301 stays quiet):
        {" ".join(f"`{n}`" for n in all_series)}

        {table}
    """)
    return tmp_path


def test_cost_model_kernel_hook_fixture(tmp_path):
    _cost_repo(tmp_path, kernel_has_cost=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC309"]
    assert any("DeviceK" in m and "cost()" in m for m in msgs)
    # host kernels (no device=TPU) are exempt
    assert not any("HostK" in m for m in msgs)


def test_cost_model_clean_fixture_is_quiet(tmp_path):
    _cost_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC309"] == []


def test_cost_model_series_all_pairings_both_directions(tmp_path):
    _cost_repo(
        tmp_path,
        declared=("scanner_tpu_eff_a", "scanner_tpu_eff_phantom"),
        registered=("scanner_tpu_eff_a", "scanner_tpu_eff_unlisted"),
        doc_series=("scanner_tpu_eff_a", "scanner_tpu_eff_ghost"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC309"]
    # registered but not declared
    assert any("scanner_tpu_eff_unlisted" in m
               and "missing from EFFICIENCY_SERIES" in m for m in msgs)
    # declared but never registered
    assert any("scanner_tpu_eff_phantom" in m
               and "registers no such series" in m for m in msgs)
    # declared but missing from the doc table
    assert any("scanner_tpu_eff_phantom" in m and "missing from the"
               in m for m in msgs)
    # doc table lists an unknown series
    assert any("scanner_tpu_eff_ghost" in m and "no such series" in m
               for m in msgs)
    assert not any("`scanner_tpu_eff_a`" in m for m in msgs)


def test_cost_model_missing_marker_table(tmp_path):
    _cost_repo(tmp_path, with_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC309"]
    assert any("marker" in m for m in msgs)


def _framecache_repo(tmp_path,
                     declared=("scanner_tpu_framecache_a",
                               "scanner_tpu_framecache_b"),
                     registered=("scanner_tpu_framecache_a",
                                 "scanner_tpu_framecache_b"),
                     doc_series=("scanner_tpu_framecache_a",
                                 "scanner_tpu_framecache_b"),
                     cfg_keys=("frame_cache_enabled", "frame_cache_mb"),
                     schema_keys=("frame_cache_enabled",
                                  "frame_cache_mb"),
                     with_markers=True):
    """Synthetic mini-repo for the SC310 frame-cache contract lints."""
    _write(tmp_path, "setup.py", "# root marker\n")
    regs = "\n        ".join(
        f'_G{i} = _mx.registry().counter("{n}", "help text", '
        f'labels=["device"])' for i, n in enumerate(registered))
    decl = ", ".join(f'"{n}"' for n in declared)
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/engine/framecache.py", f"""
        from ..util import metrics as _mx

        {regs}

        FRAMECACHE_SERIES = ({decl},)

        CONFIG_KEYS = ({schema},)
    """)
    _write(tmp_path, "pkg/util/metrics.py", """
        def registry():
            return None
    """)
    cfg = ", ".join(f'"{k}": 1' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"perf": {{{cfg}}}}}
    """)
    rows = "\n".join(f"| `{n}` | counter | x |" for n in doc_series)
    table = (f"<!-- framecache-series:begin -->\n"
             f"| Series | Type | Meaning |\n|---|---|---|\n"
             f"{rows}\n<!-- framecache-series:end -->\n"
             if with_markers else rows)
    all_series = sorted(set(declared) | set(registered) | set(doc_series))
    keys = " ".join(f"`{k}`"
                    for k in sorted(set(cfg_keys) | set(schema_keys)))
    _write(tmp_path, "docs/observability.md", f"""
        Catalog (every fixture series mentioned so SC301 stays quiet):
        {" ".join(f"`{n}`" for n in all_series)}

        Config keys documented for SC304: {keys}

        {table}
    """)
    return tmp_path


def test_framecache_clean_fixture_is_quiet(tmp_path):
    _framecache_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC310"] == []


def test_framecache_series_all_pairings_both_directions(tmp_path):
    _framecache_repo(
        tmp_path,
        declared=("scanner_tpu_framecache_a",
                  "scanner_tpu_framecache_phantom"),
        registered=("scanner_tpu_framecache_a",
                    "scanner_tpu_framecache_unlisted"),
        doc_series=("scanner_tpu_framecache_a",
                    "scanner_tpu_framecache_ghost"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC310"]
    assert any("scanner_tpu_framecache_unlisted" in m
               and "missing from FRAMECACHE_SERIES" in m for m in msgs)
    assert any("scanner_tpu_framecache_phantom" in m
               and "registers no such series" in m for m in msgs)
    assert any("scanner_tpu_framecache_phantom" in m
               and "missing from the" in m for m in msgs)
    assert any("scanner_tpu_framecache_ghost" in m
               and "no such series" in m for m in msgs)
    assert not any("`scanner_tpu_framecache_a`" in m for m in msgs)


def test_framecache_missing_marker_table(tmp_path):
    _framecache_repo(tmp_path, with_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC310"]
    assert any("marker table" in m for m in msgs)


def test_framecache_config_schema_both_directions(tmp_path):
    _framecache_repo(
        tmp_path,
        cfg_keys=("frame_cache_enabled", "frame_cache_mb",
                  "frame_cache_bogus"),
        schema_keys=("frame_cache_enabled", "frame_cache_mb",
                     "frame_cache_pages"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC310"]
    assert any("[perf] frame_cache_bogus" in m
               and "does not accept" in m for m in msgs)
    assert any("`frame_cache_pages`" in m and "declares no" in m
               for m in msgs)
    assert not any("frame_cache_enabled" in m for m in msgs)


def _fusion_repo(tmp_path,
                 declared=("scanner_tpu_fusion_a",
                           "scanner_tpu_fusion_b"),
                 registered=("scanner_tpu_fusion_a",
                             "scanner_tpu_fusion_b"),
                 doc_series=("scanner_tpu_fusion_a",
                             "scanner_tpu_fusion_b"),
                 cfg_keys=("fusion_enabled", "fusion_min_chain"),
                 schema_keys=("fusion_enabled", "fusion_min_chain"),
                 with_markers=True,
                 kernel_has_cost=True):
    """Synthetic mini-repo for the SC317 fusion contract lints."""
    _write(tmp_path, "setup.py", "# root marker\n")
    regs = "\n        ".join(
        f'_G{i} = _mx.registry().counter("{n}", "help text", '
        f'labels=["chain"])' for i, n in enumerate(registered))
    decl = ", ".join(f'"{n}"' for n in declared)
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/graph/fusion.py", f"""
        from ..util import metrics as _mx

        {regs}

        FUSION_SERIES = ({decl},)

        CONFIG_KEYS = ({schema},)
    """)
    _write(tmp_path, "pkg/util/metrics.py", """
        def registry():
            return None
    """)
    cost = ("\n            def cost(self, shapes):\n"
            "                return None\n" if kernel_has_cost else "")
    _write(tmp_path, "pkg/kernels/k.py", f"""
        class FzKernel:
            def execute(self, frame):
                return frame

            def execute_traced(self, frame):
                return frame
        {cost}
    """)
    cfg = ", ".join(f'"{k}": 1' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"perf": {{{cfg}}}}}
    """)
    rows = "\n".join(f"| `{n}` | counter | x |" for n in doc_series)
    table = (f"<!-- fusion-series:begin -->\n"
             f"| Series | Type | Meaning |\n|---|---|---|\n"
             f"{rows}\n<!-- fusion-series:end -->\n"
             if with_markers else rows)
    all_series = sorted(set(declared) | set(registered) | set(doc_series))
    keys = " ".join(f"`{k}`"
                    for k in sorted(set(cfg_keys) | set(schema_keys)))
    _write(tmp_path, "docs/observability.md", f"""
        Catalog (every fixture series mentioned so SC301 stays quiet):
        {" ".join(f"`{n}`" for n in all_series)}

        Config keys documented for SC304: {keys}

        {table}
    """)
    return tmp_path


def test_fusion_clean_fixture_is_quiet(tmp_path):
    _fusion_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC317"] == []


def test_fusion_series_all_pairings_both_directions(tmp_path):
    _fusion_repo(
        tmp_path,
        declared=("scanner_tpu_fusion_a", "scanner_tpu_fusion_phantom"),
        registered=("scanner_tpu_fusion_a",
                    "scanner_tpu_fusion_unlisted"),
        doc_series=("scanner_tpu_fusion_a", "scanner_tpu_fusion_ghost"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC317"]
    assert any("scanner_tpu_fusion_unlisted" in m
               and "missing from FUSION_SERIES" in m for m in msgs)
    assert any("scanner_tpu_fusion_phantom" in m
               and "registers no such series" in m for m in msgs)
    assert any("scanner_tpu_fusion_phantom" in m
               and "missing from" in m and "fusion-series" in m
               for m in msgs)
    assert any("scanner_tpu_fusion_ghost" in m
               and "no such series" in m for m in msgs)
    assert not any("`scanner_tpu_fusion_a`" in m for m in msgs)


def test_fusion_missing_marker_table(tmp_path):
    _fusion_repo(tmp_path, with_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC317"]
    assert any("marker table" in m for m in msgs)


def test_fusion_config_schema_both_directions(tmp_path):
    _fusion_repo(
        tmp_path,
        cfg_keys=("fusion_enabled", "fusion_min_chain", "fusion_bogus"),
        schema_keys=("fusion_enabled", "fusion_min_chain",
                     "fusion_ghost_knob"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC317"]
    assert any("[perf] fusion_bogus" in m and "does not accept" in m
               for m in msgs)
    assert any("`fusion_ghost_knob`" in m and "declares no" in m
               for m in msgs)
    assert not any("fusion_enabled" in m for m in msgs)


def test_fusion_execute_traced_without_cost(tmp_path):
    """extends SC309: a kernel advertising the fusion trace hook
    (execute_traced) without a cost() descriptor silently never fuses
    — the planner's fusability gate keys on cost()."""
    _fusion_repo(tmp_path, kernel_has_cost=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC317"]
    assert any("FzKernel" in m and "cost()" in m for m in msgs)


def _remediation_repo(tmp_path,
                      code_pbs=(("pb_a", "rule_a"), ("pb_b", "rule_b")),
                      rule_names=("rule_a", "rule_b"),
                      doc_rows=(("pb_a", "rule_a"), ("pb_b", "rule_b")),
                      cfg_keys=("enabled", "dry_run"),
                      schema_keys=("enabled", "dry_run"),
                      with_markers=True):
    """Synthetic mini-repo for the SC311 remediation contract lints."""
    _write(tmp_path, "setup.py", "# root marker\n")
    pbs = ",\n            ".join(
        f'Playbook(name="{n}", alert="{a}", action="act_{n}")'
        for n, a in code_pbs)
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/engine/controller.py", f"""
        def Playbook(**kw):
            return kw

        CONFIG_KEYS = ({schema},)

        DEFAULT_PLAYBOOKS = (
            {pbs},
        )
    """)
    rules = ",\n            ".join(
        f'Rule(name="{n}", series="scanner_tpu_x")' for n in rule_names)
    _write(tmp_path, "pkg/util/health.py", f"""
        def Rule(**kw):
            return kw

        DEFAULT_RULES = (
            {rules},
        )
    """)
    cfg = ", ".join(f'"{k}": 1' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"remediation": {{{cfg}}}}}
    """)
    rows = "\n".join(f"| `{n}` | `{a}` | act | 5 s | env |"
                     for n, a in doc_rows)
    table = (f"<!-- remediation-playbooks:begin -->\n"
             f"| Playbook | Alert | Action | Cooldown | Kill switch |\n"
             f"|---|---|---|---|---|\n"
             f"{rows}\n<!-- remediation-playbooks:end -->\n"
             if with_markers else rows)
    keys = " ".join(f"`{k}`"
                    for k in sorted(set(cfg_keys) | set(schema_keys)))
    _write(tmp_path, "docs/robustness.md", f"""
        Remediation playbook matrix:

        {table}
    """)
    _write(tmp_path, "docs/observability.md", f"""
        Config keys documented for SC304: {keys}
    """)
    return tmp_path


def test_remediation_clean_fixture_is_quiet(tmp_path):
    _remediation_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC311"] == []


def test_remediation_unknown_alert_binding(tmp_path):
    _remediation_repo(tmp_path,
                      code_pbs=(("pb_a", "rule_a"),
                                ("pb_b", "rule_ghost")),
                      doc_rows=(("pb_a", "rule_a"),
                                ("pb_b", "rule_ghost")))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC311"]
    assert any("`pb_b`" in m and "rule_ghost" in m
               and "no such rule" in m for m in msgs)
    assert not any("`pb_a`" in m for m in msgs)


def test_remediation_docs_matrix_both_directions(tmp_path):
    _remediation_repo(tmp_path,
                      code_pbs=(("pb_a", "rule_a"),
                                ("pb_undoc", "rule_b")),
                      doc_rows=(("pb_a", "rule_b"),
                                ("pb_ghost", "rule_a")))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC311"]
    # code playbook absent from docs
    assert any("`pb_undoc`" in m and "missing from" in m for m in msgs)
    # docs row with no code playbook
    assert any("`pb_ghost`" in m and "no such playbook" in m
               for m in msgs)
    # alert binding mismatch between code and the docs row
    assert any("`pb_a`" in m and "docs matrix row says" in m
               for m in msgs)


def test_remediation_missing_marker_table(tmp_path):
    _remediation_repo(tmp_path, with_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC311"]
    assert any("marker table" in m for m in msgs)


def test_remediation_config_schema_both_directions(tmp_path):
    _remediation_repo(tmp_path,
                      cfg_keys=("enabled", "dry_run", "bogus"),
                      schema_keys=("enabled", "dry_run", "min_only"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC311"]
    assert any("[remediation] bogus" in m and "does not accept" in m
               for m in msgs)
    assert any("`min_only`" in m and "declares no" in m for m in msgs)
    assert not any("enabled" in m for m in msgs)


def _fence_repo(tmp_path, wrap_mut=True, wrap_read=False,
                schema_keys=("journal_enabled",
                             "journal_rotate_records"),
                cfg_keys=("journal_enabled", "journal_rotate_records")):
    """Synthetic mini-repo for the SC312 generation-fence lints."""
    _write(tmp_path, "setup.py", "# root marker\n")
    mut = "self._fenced(self._rpc_mut)" if wrap_mut else "self._rpc_mut"
    read = "self._fenced(self._rpc_read)" if wrap_read \
        else "self._rpc_read"
    _write(tmp_path, "pkg/svc.py", f"""
        MASTER_SERVICE = "svc.Master"
        WORKER_SERVICE = "svc.Worker"

        RPC_CONTRACTS = {{
            "Mut": {{"timeout_s": 1.0, "idempotent": False}},
            "Read": {{"timeout_s": 1.0, "idempotent": True}},
        }}

        class RpcServer:
            def __init__(self, name, methods, port=0):
                pass

        class Master:
            def __init__(self):
                self._server = RpcServer(MASTER_SERVICE, {{
                    "Mut": {mut},
                    "Read": {read},
                }})

            def _fenced(self, fn):
                return fn

            def _rpc_mut(self, req):
                return {{}}

            def _rpc_read(self, req):
                return {{}}

        class Worker:
            def __init__(self):
                # worker-service registrations are outside SC312's
                # scope even when unwrapped
                self._server = RpcServer(WORKER_SERVICE, {{
                    "Read": lambda req: {{}},
                }})

        def client(c):
            c.call("Mut")
            c.call("Read")
    """)
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/engine/journal.py",
           f"CONFIG_KEYS = ({schema},)\n")
    cfg = ", ".join(f'"{k}": 1' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"robustness": {{{cfg}}}}}
    """)
    _write(tmp_path, "docs/guide.md", """
        The keys `journal_enabled`, `journal_rotate_records`,
        `journal_extra` and `journal_ghost` are documented so SC304
        stays quiet in this fixture.
    """)
    return tmp_path


def test_fence_clean_fixture_is_quiet(tmp_path):
    _fence_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC312"] == []


def test_fence_unwrapped_mutating_handler_flagged(tmp_path):
    _fence_repo(tmp_path, wrap_mut=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC312"]
    assert any("`Mut`" in m and "without the generation-fence" in m
               for m in msgs)
    assert not any("`Read`" in m for m in msgs)


def test_fence_wrapped_idempotent_handler_flagged(tmp_path):
    _fence_repo(tmp_path, wrap_read=True)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC312"]
    assert any("`Read`" in m and "idempotent=False" in m for m in msgs)
    assert not any("`Mut`" in m for m in msgs)


def test_fence_journal_config_keys_both_directions(tmp_path):
    _fence_repo(tmp_path,
                schema_keys=("journal_enabled", "journal_ghost"),
                cfg_keys=("journal_enabled", "journal_extra"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC312"]
    assert any("journal_extra" in m and "does not accept" in m
               for m in msgs)
    assert any("journal_ghost" in m and "declares no" in m
               for m in msgs)
    assert not any("journal_enabled" in m for m in msgs)


def _gang_repo(tmp_path, wrap_gang=True, idem_gang=False,
               schema_keys=("enabled", "init_timeout_s"),
               cfg_keys=("enabled", "init_timeout_s"),
               doc_keys=("enabled", "init_timeout_s")):
    """Synthetic mini-repo for the SC313 gang-contract lints."""
    _write(tmp_path, "setup.py", "# root marker\n")
    gm = "self._fenced(self._rpc_gang)" if wrap_gang \
        else "self._rpc_gang"
    idem = "True" if idem_gang else "False"
    _write(tmp_path, "pkg/svc.py", f"""
        MASTER_SERVICE = "svc.Master"

        RPC_CONTRACTS = {{
            "GangFailed": {{"timeout_s": 1.0, "idempotent": {idem}}},
            "Read": {{"timeout_s": 1.0, "idempotent": True}},
        }}

        class RpcServer:
            def __init__(self, name, methods, port=0):
                pass

        class Master:
            def __init__(self):
                self._server = RpcServer(MASTER_SERVICE, {{
                    "GangFailed": {gm},
                    "Read": self._rpc_read,
                }})

            def _fenced(self, fn):
                return fn

            def _rpc_gang(self, req):
                return {{}}

            def _rpc_read(self, req):
                return {{}}

        def client(c):
            c.call("GangFailed")
            c.call("Read")
    """)
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/engine/gang.py",
           f"CONFIG_KEYS = ({schema},)\n")
    cfg = ", ".join(f'"{k}": 1' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"gang": {{{cfg}}}}}
    """)
    rows = "\n".join(f"| `[gang] {k}` | a row |" for k in doc_keys)
    _write(tmp_path, "docs/guide.md", f"""
        The keys `enabled`, `init_timeout_s`, `ghost_key` and
        `extra_key` are mentioned so SC304 stays quiet.

        {rows}
    """)
    return tmp_path


def test_gang_clean_fixture_is_quiet(tmp_path):
    _gang_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC313"] == []


def test_gang_unfenced_handler_flagged(tmp_path):
    _gang_repo(tmp_path, wrap_gang=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC313"]
    assert any("`GangFailed`" in m and "generation-fence" in m
               for m in msgs)


def test_gang_misclassified_idempotent_flagged(tmp_path):
    """SC312 cannot see a Gang entry misclassified idempotent=True
    (it only inspects idempotent=False entries) — SC313 pins the gang
    surface from the other side."""
    _gang_repo(tmp_path, idem_gang=True)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC313"]
    assert any("`GangFailed`" in m and "idempotent=False" in m
               for m in msgs)
    assert not any("`Read`" in m for m in msgs)


def test_gang_config_keys_all_pairings(tmp_path):
    _gang_repo(tmp_path,
               schema_keys=("enabled", "ghost_key"),
               cfg_keys=("enabled", "extra_key"),
               doc_keys=("enabled",))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC313"]
    # config declares a key the module refuses
    assert any("extra_key" in m and "does not accept" in m
               for m in msgs)
    # module accepts a key config never declares
    assert any("ghost_key" in m and "declares no" in m for m in msgs)
    # module accepts a key guide.md has no row for
    assert any("ghost_key" in m and "guide.md" in m for m in msgs)
    assert not any("`enabled`" in m for m in msgs)


def test_gang_doc_row_without_schema_key_flagged(tmp_path):
    _gang_repo(tmp_path, doc_keys=("enabled", "init_timeout_s",
                                   "phantom_row"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC313"]
    assert any("phantom_row" in m and "no such key" in m for m in msgs)


def _clocksync_repo(tmp_path,
                    cs_declared=("scanner_tpu_clock_offset_seconds",
                                 "scanner_tpu_clock_uncert_seconds"),
                    cs_registered=("scanner_tpu_clock_offset_seconds",
                                   "scanner_tpu_clock_uncert_seconds"),
                    gp_declared=("scanner_tpu_gang_phase_seconds",),
                    gp_registered=("scanner_tpu_gang_phase_seconds",),
                    doc_series=None,
                    spans=("gang.rendezvous", "gang.barrier"),
                    doc_spans=None,
                    cfg_keys=("enabled", "clocksync_enabled",
                              "rebase_clocks"),
                    schema_keys=("clocksync_enabled", "rebase_clocks"),
                    with_series_markers=True,
                    with_span_markers=True):
    """Synthetic mini-repo for the SC314 cross-host time lints.

    gang.py always also registers a lifecycle counter that is NOT in
    GANG_PHASE_SERIES — the reverse leg must only claim
    phase/skew-named series, not every gang metric."""
    if doc_series is None:
        doc_series = tuple(cs_declared) + tuple(gp_declared)
    if doc_spans is None:
        doc_spans = spans
    _write(tmp_path, "setup.py", "# root marker\n")
    regs = "\n        ".join(
        f'_G{i} = _mx.registry().gauge("{n}", "help text", '
        f'labels=["node"])' for i, n in enumerate(cs_registered))
    decl = ", ".join(f'"{n}"' for n in cs_declared)
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/util/clocksync.py", f"""
        from . import metrics as _mx

        {regs}

        CLOCKSYNC_SERIES = ({decl},)

        CONFIG_KEYS = ({schema},)
    """)
    gregs = "\n        ".join(
        f'_P{i} = _mx.registry().counter("{n}", "help text", '
        f'labels=["phase"])' for i, n in enumerate(gp_registered))
    gdecl = ", ".join(f'"{n}"' for n in gp_declared)
    opens = "\n            ".join(
        f'_tr.open_span(None, "{s}")' for s in spans)
    _write(tmp_path, "pkg/engine/gang.py", f"""
        from ..util import metrics as _mx
        from ..util import tracing as _tr

        _M_FORMED = _mx.registry().counter(
            "scanner_tpu_gang_formed_total", "help text")

        {gregs}

        GANG_PHASE_SERIES = ({gdecl},)

        def member():
            {opens}
    """)
    _write(tmp_path, "pkg/util/metrics.py", """
        def registry():
            return None
    """)
    _write(tmp_path, "pkg/util/tracing.py", """
        def open_span(tracer, name, **kw):
            return None
    """)
    cfg = ", ".join(f'"{k}": True' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"trace": {{{cfg}}}}}
    """)
    rows = "\n".join(f"| `{n}` | gauge | x |" for n in doc_series)
    stable = (f"<!-- clocksync-series:begin -->\n"
              f"| Series | Type | Meaning |\n|---|---|---|\n"
              f"{rows}\n<!-- clocksync-series:end -->\n"
              if with_series_markers else rows)
    srows = "\n".join(f"| `{s}` | a phase |" for s in doc_spans)
    ptable = (f"<!-- gang-phase-taxonomy:begin -->\n"
              f"| Span | Meaning |\n|---|---|\n"
              f"{srows}\n<!-- gang-phase-taxonomy:end -->\n"
              if with_span_markers else srows)
    all_series = sorted(set(cs_declared) | set(cs_registered)
                        | set(gp_declared) | set(gp_registered)
                        | set(doc_series)
                        | {"scanner_tpu_gang_formed_total"})
    keys = " ".join(f"`{k}`"
                    for k in sorted(set(cfg_keys) | set(schema_keys)))
    _write(tmp_path, "docs/observability.md", f"""
        Catalog (every fixture series mentioned so SC301 stays quiet):
        {" ".join(f"`{n}`" for n in all_series)}

        Config keys documented for SC304: {keys}

        {stable}

        {ptable}
    """)
    return tmp_path


def test_clocksync_clean_fixture_is_quiet(tmp_path):
    _clocksync_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC314"] == []


def test_clocksync_series_all_pairings_both_directions(tmp_path):
    _clocksync_repo(
        tmp_path,
        cs_declared=("scanner_tpu_clock_offset_seconds",
                     "scanner_tpu_clock_phantom"),
        cs_registered=("scanner_tpu_clock_offset_seconds",
                       "scanner_tpu_clock_unlisted"),
        doc_series=("scanner_tpu_clock_offset_seconds",
                    "scanner_tpu_gang_phase_seconds",
                    "scanner_tpu_clock_ghost"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC314"]
    assert any("scanner_tpu_clock_unlisted" in m
               and "missing from CLOCKSYNC_SERIES" in m for m in msgs)
    assert any("scanner_tpu_clock_phantom" in m
               and "registers no such series" in m for m in msgs)
    assert any("scanner_tpu_clock_phantom" in m
               and "missing from the" in m for m in msgs)
    assert any("scanner_tpu_clock_ghost" in m
               and "has such a series" in m for m in msgs)
    assert not any("`scanner_tpu_clock_offset_seconds`" in m
                   for m in msgs)


def test_clocksync_gang_phase_series_scoped_to_phase_names(tmp_path):
    """The reverse leg on gang.py must flag an undeclared
    phase/skew-named registration but NOT the lifecycle counters the
    module also owns (SC310's exact-pairing shape would false-positive
    on every gang metric)."""
    _clocksync_repo(
        tmp_path,
        gp_declared=("scanner_tpu_gang_phase_seconds",),
        gp_registered=("scanner_tpu_gang_phase_seconds",
                       "scanner_tpu_gang_barrier_skew_seconds"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC314"]
    assert any("scanner_tpu_gang_barrier_skew_seconds" in m
               and "missing from GANG_PHASE_SERIES" in m for m in msgs)
    assert not any("scanner_tpu_gang_formed_total" in m for m in msgs)


def test_clocksync_missing_marker_tables(tmp_path):
    _clocksync_repo(tmp_path, with_series_markers=False,
                    with_span_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC314"]
    assert any("clocksync-series" in m and "marker table" in m
               for m in msgs)
    assert any("gang-phase-taxonomy" in m and "marker table" in m
               for m in msgs)


def test_clocksync_span_taxonomy_both_directions(tmp_path):
    _clocksync_repo(
        tmp_path,
        spans=("gang.rendezvous", "gang.barrier", "gang.stealth"),
        doc_spans=("gang.rendezvous", "gang.barrier", "gang.phantom"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC314"]
    assert any("`gang.stealth`" in m and "no row" in m for m in msgs)
    assert any("`gang.phantom`" in m and "opens no" in m for m in msgs)
    assert not any("`gang.barrier`" in m for m in msgs)


def test_clocksync_trace_config_keys_both_directions(tmp_path):
    """`[trace] enabled` belongs to the tracing core and is exempt;
    every other [trace] key must pair with clocksync.CONFIG_KEYS."""
    _clocksync_repo(
        tmp_path,
        cfg_keys=("enabled", "clocksync_enabled", "bogus_key"),
        schema_keys=("clocksync_enabled", "ghost_key"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC314"]
    assert any("bogus_key" in m and "does not accept" in m
               for m in msgs)
    assert any("ghost_key" in m and "declares no" in m for m in msgs)
    assert not any("`enabled`" in m for m in msgs)
    assert not any("clocksync_enabled" in m for m in msgs)


_SHARD_FIX_SERIES = ("scanner_tpu_gang_shard_rows_total",
                     "scanner_tpu_gang_shard_commit_folds_total")


def _gang_shard_repo(tmp_path,
                     declared=_SHARD_FIX_SERIES,
                     registered=None,
                     doc_series=None,
                     schema_keys=("enabled", "sharded",
                                  "halo_exchange"),
                     cfg_keys=("enabled", "sharded", "halo_exchange"),
                     with_markers=True,
                     with_tuple=True):
    """Synthetic mini-repo for the SC315 sharded-gang data-plane
    lints.  gang.py also registers a lifecycle counter NOT named
    `_shard_` — the reverse leg must only claim shard-named series."""
    if registered is None:
        registered = declared
    if doc_series is None:
        doc_series = declared
    _write(tmp_path, "setup.py", "# root marker\n")
    regs = "\n        ".join(
        f'_S{i} = _mx.registry().counter("{n}", "help text", '
        f'labels=["role"])' for i, n in enumerate(registered))
    decl = (f"GANG_SHARD_SERIES = ("
            + ", ".join(f'"{n}"' for n in declared) + ",)"
            if with_tuple else "")
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/engine/gang.py", f"""
        from ..util import metrics as _mx

        _M_FORMED = _mx.registry().counter(
            "scanner_tpu_gang_formed_total", "help text")

        {regs}

        {decl}

        CONFIG_KEYS = ({schema},)
    """)
    _write(tmp_path, "pkg/util/metrics.py", """
        def registry():
            return None
    """)
    cfg = ", ".join(f'"{k}": True' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"gang": {{{cfg}}}}}
    """)
    rows = "\n".join(f"| `{n}` | counter | `role` | x |"
                     for n in doc_series)
    stable = (f"<!-- gang-shard-series:begin -->\n"
              f"| Series | Type | Labels | Meaning |\n|---|---|---|"
              f"---|\n{rows}\n<!-- gang-shard-series:end -->\n"
              if with_markers else rows)
    all_series = sorted(set(declared) | set(registered)
                        | set(doc_series)
                        | {"scanner_tpu_gang_formed_total"})
    _write(tmp_path, "docs/observability.md", f"""
        Catalog (every fixture series mentioned so SC301 stays
        quiet): {" ".join(f"`{n}`" for n in all_series)}

        {stable}
    """)
    gkeys = "\n".join(f"| `[gang] {k}` | a row |"
                      for k in sorted(set(schema_keys)
                                      | set(cfg_keys)))
    _write(tmp_path, "docs/guide.md", f"""
        Keys mentioned so SC304 stays quiet: `enabled` `sharded`
        `halo_exchange`

        {gkeys}
    """)
    return tmp_path


def test_gang_shard_clean_fixture_is_quiet(tmp_path):
    _gang_shard_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC315"] == []


def test_gang_shard_series_all_pairings_both_directions(tmp_path):
    _gang_shard_repo(
        tmp_path,
        declared=("scanner_tpu_gang_shard_rows_total",
                  "scanner_tpu_gang_shard_phantom_total"),
        registered=("scanner_tpu_gang_shard_rows_total",
                    "scanner_tpu_gang_shard_unlisted_total"),
        doc_series=("scanner_tpu_gang_shard_rows_total",
                    "scanner_tpu_gang_shard_ghost_total"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC315"]
    assert any("scanner_tpu_gang_shard_unlisted_total" in m
               and "missing from GANG_SHARD_SERIES" in m for m in msgs)
    assert any("scanner_tpu_gang_shard_phantom_total" in m
               and "registers no such series" in m for m in msgs)
    assert any("scanner_tpu_gang_shard_phantom_total" in m
               and "missing from the" in m for m in msgs)
    assert any("scanner_tpu_gang_shard_ghost_total" in m
               and "no such series" in m for m in msgs)
    assert not any("`scanner_tpu_gang_shard_rows_total`" in m
                   for m in msgs)
    # the lifecycle counter the module also owns is NOT claimed
    assert not any("scanner_tpu_gang_formed_total" in m for m in msgs)


def test_gang_shard_missing_marker_table(tmp_path):
    _gang_shard_repo(tmp_path, with_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC315"]
    assert any("gang-shard-series" in m and "marker table" in m
               for m in msgs)


def test_gang_shard_missing_tuple_flagged(tmp_path):
    _gang_shard_repo(tmp_path, with_tuple=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC315"]
    assert any("declares no GANG_SHARD_SERIES tuple" in m
               for m in msgs)


def test_gang_shard_gate_keys_travel_with_plane(tmp_path):
    """The data plane without its `[gang]` gates — both the schema
    side (kill switch) and the config side (declared default)."""
    _gang_shard_repo(tmp_path,
                     schema_keys=("enabled", "sharded"),
                     cfg_keys=("enabled", "halo_exchange"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC315"]
    assert any("halo_exchange" in m and "kill switch" in m
               for m in msgs)
    assert any("sharded" in m and "declared default" in m
               for m in msgs)


def test_gang_shard_gate_without_plane_flagged(tmp_path):
    """CONFIG_KEYS carrying the sharding gates while the module has
    no shard data plane at all — stale gate surface."""
    _gang_shard_repo(tmp_path, registered=(), with_tuple=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC315"]
    assert any("sharded" in m and "nothing to gate" in m
               for m in msgs)
    assert any("halo_exchange" in m and "nothing to gate" in m
               for m in msgs)


_SHARDMAP_FIX_SERIES = ("scanner_tpu_shard_map_epoch",
                        "scanner_tpu_shard_failovers_total")


def _shardmap_repo(tmp_path,
                   declared=_SHARDMAP_FIX_SERIES,
                   registered=None,
                   doc_series=None,
                   schema_keys=("shards",),
                   cfg_keys=("shards",),
                   routed=("Mut",),
                   wrap_mut=True,
                   with_markers=True,
                   with_tuple=True):
    """Synthetic mini-repo for the SC316 sharded control-plane
    lints: a shardmap module with its series catalog + [control]
    schema, and a master service whose SHARD_ROUTED_RPCS tuple must
    agree with the idempotent=False, fence-wrapped surface."""
    if registered is None:
        registered = declared
    if doc_series is None:
        doc_series = declared
    _write(tmp_path, "setup.py", "# root marker\n")
    regs = "\n        ".join(
        f'_S{i} = _mx.registry().counter("{n}", "help text", '
        f'labels=["role"])' for i, n in enumerate(registered))
    decl = (f"SHARD_SERIES = ("
            + ", ".join(f'"{n}"' for n in declared) + ",)"
            if with_tuple else "")
    schema = ", ".join(f'"{k}"' for k in schema_keys)
    _write(tmp_path, "pkg/engine/shardmap.py", f"""
        from ..util import metrics as _mx

        {regs}

        {decl}

        CONFIG_KEYS = ({schema},)
    """)
    _write(tmp_path, "pkg/util/metrics.py", """
        def registry():
            return None
    """)
    mut = "self._fenced(self._rpc_mut)" if wrap_mut \
        else "self._rpc_mut"
    routed_decl = "SHARD_ROUTED_RPCS = (" \
        + "".join(f'"{r}", ' for r in routed) + ")"
    _write(tmp_path, "pkg/engine/service.py", f"""
        MASTER_SERVICE = "svc.Master"

        RPC_CONTRACTS = {{
            "Mut": {{"timeout_s": 1.0, "idempotent": False}},
            "Read": {{"timeout_s": 1.0, "idempotent": True}},
        }}

        {routed_decl}

        class RpcServer:
            def __init__(self, name, methods, port=0):
                pass

        class Master:
            def __init__(self):
                self._server = RpcServer(MASTER_SERVICE, {{
                    "Mut": {mut},
                    "Read": self._rpc_read,
                }})

            def _fenced(self, fn):
                return fn

            def _rpc_mut(self, req):
                return {{}}

            def _rpc_read(self, req):
                return {{}}

        def client(c):
            c.call("Mut")
            c.call("Read")
    """)
    cfg = ", ".join(f'"{k}": 1' for k in cfg_keys)
    _write(tmp_path, "pkg/config.py", f"""
        def default_config():
            return {{"control": {{{cfg}}}}}
    """)
    rows = "\n".join(f"| `{n}` | counter | `role` | x |"
                     for n in doc_series)
    stable = (f"<!-- shard-series:begin -->\n"
              f"| Series | Type | Labels | Meaning |\n|---|---|---|"
              f"---|\n{rows}\n<!-- shard-series:end -->\n"
              if with_markers else rows)
    all_series = sorted(set(declared) | set(registered)
                        | set(doc_series))
    _write(tmp_path, "docs/observability.md", f"""
        Catalog (every fixture series mentioned so SC301 stays
        quiet): {" ".join(f"`{n}`" for n in all_series)}

        {stable}
    """)
    ckeys = " ".join(f"`{k}`" for k in sorted(set(schema_keys)
                                              | set(cfg_keys)))
    _write(tmp_path, "docs/guide.md", f"""
        Keys mentioned so SC304 stays quiet: {ckeys}
    """)
    return tmp_path


def test_shardmap_clean_fixture_is_quiet(tmp_path):
    _shardmap_repo(tmp_path)
    _, findings = _analyze(tmp_path, "pkg")
    assert [f for f in findings if f.code == "SC316"] == []


def test_shardmap_series_all_pairings_both_directions(tmp_path):
    _shardmap_repo(
        tmp_path,
        declared=("scanner_tpu_shard_map_epoch",
                  "scanner_tpu_shard_phantom_total"),
        registered=("scanner_tpu_shard_map_epoch",
                    "scanner_tpu_shard_unlisted_total"),
        doc_series=("scanner_tpu_shard_map_epoch",
                    "scanner_tpu_shard_ghost_total"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("scanner_tpu_shard_unlisted_total" in m
               and "missing from SHARD_SERIES" in m for m in msgs)
    assert any("scanner_tpu_shard_phantom_total" in m
               and "registers no such series" in m for m in msgs)
    assert any("scanner_tpu_shard_phantom_total" in m
               and "missing from the" in m for m in msgs)
    assert any("scanner_tpu_shard_ghost_total" in m
               and "no such series" in m for m in msgs)
    assert not any("`scanner_tpu_shard_map_epoch`" in m
                   for m in msgs)


def test_shardmap_missing_marker_table(tmp_path):
    _shardmap_repo(tmp_path, with_markers=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("shard-series" in m and "marker table" in m
               for m in msgs)


def test_shardmap_missing_tuple_flagged(tmp_path):
    _shardmap_repo(tmp_path, with_tuple=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("declares no SHARD_SERIES tuple" in m for m in msgs)


def test_shardmap_control_config_keys_both_directions(tmp_path):
    _shardmap_repo(tmp_path,
                   schema_keys=("shards", "schema_only"),
                   cfg_keys=("shards", "cfg_only"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("[control] cfg_only" in m and "does not accept" in m
               for m in msgs)
    assert any("`schema_only`" in m and "declares no" in m
               for m in msgs)
    assert not any("`shards`" in m for m in msgs)


def test_shardmap_routed_rpc_must_be_mutating(tmp_path):
    """Routing an idempotent read through bulk-ownership dispatch is
    flagged — only mutating RPCs follow the bulk to its shard."""
    _shardmap_repo(tmp_path, routed=("Mut", "Read"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("`Read`" in m and "idempotent=False" in m
               for m in msgs)
    assert not any("`Mut`" in m for m in msgs)


def test_shardmap_mutating_rpc_must_be_routed(tmp_path):
    """An idempotent=False contract missing from SHARD_ROUTED_RPCS
    would pin mutations to the dial-time shard."""
    _shardmap_repo(tmp_path, routed=())
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("`Mut`" in m and "missing from SHARD_ROUTED_RPCS" in m
               for m in msgs)
    assert not any("`Read`" in m for m in msgs)


def test_shardmap_routed_rpc_must_stay_fenced(tmp_path):
    """A shard-routed handler outside the generation fence reopens
    the stale-master window (the SC312 extension leg)."""
    _shardmap_repo(tmp_path, wrap_mut=False)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("`Mut`" in m and "without the generation-fence" in m
               for m in msgs)


def test_shardmap_routed_phantom_method_flagged(tmp_path):
    _shardmap_repo(tmp_path, routed=("Mut", "Ghost"))
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC316"]
    assert any("`Ghost`" in m and "no such entry" in m for m in msgs)


def test_contract_rpc_contracts_table_both_directions(tmp_path):
    _write(tmp_path, "setup.py", "# root\n")
    _write(tmp_path, "pkg/rpcmod.py", """
        RPC_CONTRACTS = {
            "Reg": {"timeout_s": 1.0, "idempotent": True},
            "Phantom": {"timeout_s": 1.0, "idempotent": True},
        }

        class RpcServer:
            def __init__(self, name, methods, port=0):
                pass

        def serve(h):
            return RpcServer("svc", {"Reg": h, "Unclassified": h})

        def client(c):
            c.call("Reg")
            c.call("Unclassified")
    """)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC307"]
    assert any("Unclassified" in m for m in msgs)
    assert any("Phantom" in m for m in msgs)
    assert not any("`Reg`" in m for m in msgs)


def test_contract_rpc_contracts_entry_completeness(tmp_path):
    """SC307 also rejects present-but-incomplete entries: every
    classification needs BOTH `timeout_s` and `idempotent`, as dict
    literals the lint can see."""
    _write(tmp_path, "setup.py", "# root\n")
    _write(tmp_path, "pkg/rpcmod.py", """
        TIMEOUTS = {"timeout_s": 1.0}

        RPC_CONTRACTS = {
            "Full": {"timeout_s": 1.0, "idempotent": True},
            "NoIdem": {"timeout_s": 1.0},
            "NoTimeout": {"idempotent": True},
            "NotADict": TIMEOUTS,
        }

        class RpcServer:
            def __init__(self, name, methods, port=0):
                pass

        def serve(h):
            return RpcServer("svc", {"Full": h, "NoIdem": h,
                                     "NoTimeout": h, "NotADict": h})

        def client(c):
            c.call("Full")
            c.call("NoIdem")
            c.call("NoTimeout")
            c.call("NotADict")
    """)
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC307"]
    assert any("NoIdem" in m and "idempotent" in m for m in msgs)
    assert any("NoTimeout" in m and "timeout_s" in m for m in msgs)
    assert any("NotADict" in m and "dict literal" in m for m in msgs)
    assert not any("`Full`" in m for m in msgs)


# ---------------------------------------------------------------------------
# suppression + baseline round-trip
# ---------------------------------------------------------------------------

SLEEPY = """
    import threading
    import time

    class S:
        def __init__(self):
            self.l = threading.Lock()

        def slow(self):
            with self.l:
                time.sleep(1)
"""


def test_inline_suppression(tmp_path):
    _write(tmp_path, "s.py", SLEEPY.replace(
        "time.sleep(1)",
        "time.sleep(1)  # scanner-check: disable=SC202 test shim"))
    proj, findings = _analyze(tmp_path)
    res = split_findings(proj, findings)
    assert not res.unsuppressed
    assert [f.code for f in res.inline_suppressed] == ["SC202"]


def test_file_level_suppression(tmp_path):
    _write(tmp_path, "s.py",
           "# scanner-check: disable-file=SC202\n" + textwrap.dedent(
               SLEEPY))
    proj, findings = _analyze(tmp_path)
    res = split_findings(proj, findings)
    assert not res.unsuppressed and res.inline_suppressed


def test_baseline_round_trip(tmp_path):
    _write(tmp_path, "s.py", SLEEPY)
    proj, findings = _analyze(tmp_path)
    res = split_findings(proj, findings)
    assert [f.code for f in res.unsuppressed] == ["SC202"]

    bl_path = str(tmp_path / "baseline.json")
    new = write_baseline(bl_path, res.unsuppressed)
    assert new == 1
    # placeholder justification must be rejected
    with pytest.raises(BaselineError):
        load_baseline(bl_path)
    doc = json.load(open(bl_path))
    doc["entries"][0]["justification"] = "intentional for the test"
    json.dump(doc, open(bl_path, "w"))
    baseline = load_baseline(bl_path)

    # baselined finding no longer reported...
    res2 = split_findings(proj, findings, baseline)
    assert not res2.unsuppressed
    assert [f.code for f in res2.baselined] == ["SC202"]

    # ...and the fingerprint survives the code MOVING (line shift)
    _write(tmp_path, "s.py", "# a new leading comment\n\n"
           + textwrap.dedent(SLEEPY))
    proj3, findings3 = _analyze(tmp_path)
    res3 = split_findings(proj3, findings3, baseline)
    assert not res3.unsuppressed and res3.baselined

    # fixing the code makes the entry STALE (prunable), not silent
    _write(tmp_path, "s.py", textwrap.dedent(SLEEPY).replace(
        "time.sleep(1)", "pass"))
    proj4, findings4 = _analyze(tmp_path)
    res4 = split_findings(proj4, findings4, baseline)
    assert not res4.unsuppressed
    assert len(res4.stale_baseline) == 1

    # re-writing keeps existing justifications
    _write(tmp_path, "s.py", SLEEPY)
    proj5, findings5 = _analyze(tmp_path)
    res5 = split_findings(proj5, findings5)
    assert write_baseline(bl_path, res5.unsuppressed,
                          previous=baseline) == 0
    assert load_baseline(bl_path)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path):
    _write(tmp_path, "setup.py", "# root\n")
    bad = _write(tmp_path, "pkg/s.py", SLEEPY)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanner_check.py"),
         bad, "--root", str(tmp_path), "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["counts"] == {"SC202": 1}
    assert doc["findings"][0]["path"] == "pkg/s.py"

    clean = _write(tmp_path, "pkg/ok.py", "x = 1\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanner_check.py"),
         clean, "--root", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr

    # --write-baseline under --select must refuse: a selected run can't
    # see other codes' findings, so a rewrite would erase their entries
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanner_check.py"),
         bad, "--root", str(tmp_path), "--select", "SC3",
         "--write-baseline"],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 2 and "erase" in r.stderr, \
        r.stdout + r.stderr


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------

def test_repo_is_clean():
    """THE gate: scanner-check over the whole package reports zero
    unsuppressed findings.  A new finding means: fix it, or suppress it
    inline / baseline it WITH a one-line justification (reviewed like
    code).  load_baseline() already rejects justification-less entries,
    so a clean pass here also certifies the baseline's hygiene."""
    baseline_path = os.path.join(REPO, "tools",
                                 "scanner_check_baseline.json")
    baseline = load_baseline(baseline_path)   # raises on TODO entries
    pkg = os.path.join(REPO, "scanner_tpu")
    proj, findings = analyze([pkg], root=REPO)
    res = split_findings(proj, findings, baseline)
    assert not res.unsuppressed, \
        "scanner-check found new issues:\n" + "\n".join(
            f.format() for f in res.unsuppressed)
    assert not res.stale_baseline, \
        ("baseline entries no longer match any finding — prune them "
         f"(tools/scanner_check.py --write-baseline): "
         f"{res.stale_baseline}")


def test_run_analysis_select():
    findings = run_analysis([os.path.join(REPO, "scanner_tpu")],
                            root=REPO, select=["SC2"])
    assert all(f.code.startswith("SC2") for f in findings)


# ---------------------------------------------------------------------------
# family 4: durability & fencing (SC401-SC406)
# ---------------------------------------------------------------------------

def _sc4(findings):
    return sorted(f.code for f in findings if f.code.startswith("SC4"))


DUR_WRITE_AHEAD_BAD = """
    import threading

    MASTER_SERVICE = "scanner-master"
    RECORD_TYPES = ("done",)

    class RpcServer:
        def __init__(self, name, methods, port=0):
            pass

    class Master:
        def __init__(self):
            self._fence = threading.Event()
            self.done = set()

        def _fenced(self, h):
            return h

        def _journal_append(self, recs):
            if self._fence.is_set():
                return

        def _apply(self, rec):
            t = rec.get("t")
            if t == "done":
                self.done.add(rec["task"])

        def _rpc_finish(self, req):
            recs = []
            recs.append({"t": "done", "task": req["task"]})
            self.done.add(req["task"])
            if req.get("fast"):
                return {"ok": True}
            self._journal_append(recs)
            return {"ok": True}

        def serve(self):
            return RpcServer(MASTER_SERVICE, {
                "FinishedWork": self._fenced(self._rpc_finish),
            })
"""

DUR_WRITE_AHEAD_CLEAN = DUR_WRITE_AHEAD_BAD.replace(
    """\
        def _rpc_finish(self, req):
            recs = []
            recs.append({"t": "done", "task": req["task"]})
            self.done.add(req["task"])
            if req.get("fast"):
                return {"ok": True}
            self._journal_append(recs)
            return {"ok": True}
""",
    """\
        def _rpc_finish(self, req):
            recs = []
            try:
                recs.append({"t": "done", "task": req["task"]})
                self.done.add(req["task"])
                if req.get("fast"):
                    return {"ok": True, "fast": True}
                return {"ok": True}
            finally:
                self._journal_append(recs)
""")


def test_write_ahead_dirty_ack_flagged(tmp_path):
    _write(tmp_path, "m.py", DUR_WRITE_AHEAD_BAD)
    _, findings = _analyze(tmp_path)
    sc401 = [f for f in findings if f.code == "SC401"]
    assert len(sc401) == 1
    assert "_rpc_finish" in sc401[0].message
    assert "FinishedWork" in sc401[0].message


def test_write_ahead_finally_commit_is_clean(tmp_path):
    """The journal-in-finally idiom: every return flows through the
    enclosing finally's group-commit first, so no path acks dirty."""
    _write(tmp_path, "m.py", DUR_WRITE_AHEAD_CLEAN)
    _, findings = _analyze(tmp_path)
    assert _sc4(findings) == []


def test_write_ahead_inline_suppression(tmp_path):
    _write(tmp_path, "m.py", DUR_WRITE_AHEAD_BAD.replace(
        "return {\"ok\": True}\n            self._journal_append",
        "return {\"ok\": True}  "
        "# scanner-check: disable=SC401 volatile-only fast path\n"
        "            self._journal_append"))
    proj, findings = _analyze(tmp_path)
    res = split_findings(proj, findings)
    assert not [f for f in res.unsuppressed if f.code == "SC401"]
    assert [f.code for f in res.inline_suppressed] == ["SC401"]


DUR_FENCE_BAD = """
    import threading

    MASTER_SERVICE = "scanner-master"
    RECORD_TYPES = ("strike",)

    class RpcServer:
        def __init__(self, name, methods, port=0):
            pass

    class Master:
        def __init__(self):
            self.transient_failures = {}

        def _journal_append(self, recs):
            pass

        def _apply(self, rec):
            t = rec.get("t")
            if t == "strike":
                self.transient_failures.pop(rec["w"], None)

        def _rpc_unreg(self, req):
            recs = self._requeue(req["worker"])
            self._journal_append(recs)
            return {"ok": True}

        def _requeue(self, wid):
            self.transient_failures.update({wid: 1})
            return [{"t": "strike", "w": wid}]

        def serve(self):
            return RpcServer(MASTER_SERVICE, {
                "UnregisterWorker": self._rpc_unreg,
            })
"""

# the real fix's idiom: the unfenced handler consults the fence before
# reaching the durable mutation, so it participates in the protocol
DUR_FENCE_CLEAN = DUR_FENCE_BAD.replace(
    """\
        def _rpc_unreg(self, req):
            recs = self._requeue(req["worker"])
""",
    """\
        def _rpc_unreg(self, req):
            if self._fence.is_set():
                return {"ok": True}
            recs = self._requeue(req["worker"])
""")


def test_fence_unfenced_handler_mutation_flagged(tmp_path):
    _write(tmp_path, "m.py", DUR_FENCE_BAD)
    _, findings = _analyze(tmp_path)
    sc402 = [f for f in findings if f.code == "SC402"]
    assert len(sc402) == 1
    assert "_requeue" in sc402[0].message
    assert "UnregisterWorker" in sc402[0].message


def test_fence_consulting_handler_is_clean(tmp_path):
    _write(tmp_path, "m.py", DUR_FENCE_CLEAN)
    _, findings = _analyze(tmp_path)
    assert _sc4(findings) == []


def test_fence_background_thread_target_flagged(tmp_path):
    """Thread(target=self.X) is an entry point the fence audit follows,
    same as an unfenced handler."""
    _write(tmp_path, "m.py", DUR_FENCE_BAD.replace(
        """\
        def serve(self):
""",
        """\
        def start(self):
            threading.Thread(target=self._scan, daemon=True).start()

        def _scan(self):
            self._requeue(0)

        def serve(self):
"""))
    _, findings = _analyze(tmp_path)
    msgs = [f.message for f in findings if f.code == "SC402"]
    assert any("background thread `_scan`" in m for m in msgs)


DUR_STALE_BAD = """
    class ShardState:
        def __init__(self):
            self.committed_jobs = set()
            self.map_epoch = 0

        def apply_equality(self, msg):
            e = msg.get("map_epoch")
            if e == self.map_epoch:
                self.committed_jobs.add(msg["job"])
            return True

        def apply_blind(self, msg):
            self.map_epoch = msg["map_epoch"]
            self.committed_jobs.add(msg["job"])
"""

DUR_STALE_CLEAN = """
    class ShardState:
        def __init__(self):
            self.committed_jobs = set()
            self.map_epoch = 0

        def apply_monotone(self, msg):
            e = msg.get("map_epoch")
            if e <= self.map_epoch:
                return False
            self.map_epoch = e
            self.committed_jobs.add(msg["job"])
            return True

        def apply_cas(self, msg):
            if not try_claim(msg["epoch"]):
                return False
            self.committed_jobs.add(msg["job"])
            return True

        def apply_delegated(self, msg):
            self._validate(msg)
            self.committed_jobs.add(msg["job"])

        def apply_latch(self, msg):
            self.map_epoch = max(self.map_epoch, msg["map_epoch"])

        def _validate(self, msg):
            return True
"""


def test_staleness_equality_check_flagged(tmp_path):
    _write(tmp_path, "m.py", DUR_STALE_BAD)
    _, findings = _analyze(tmp_path)
    msgs = [f.message for f in findings if f.code == "SC403"]
    assert len(msgs) == 2
    assert any("apply_equality" in m and "equality" in m for m in msgs)
    assert any("apply_blind" in m and "without any" in m for m in msgs)


def test_staleness_monotone_cas_delegation_clean(tmp_path):
    """Monotone compares, CAS claims, max()-latches, and passing the
    stamped message to a validator all count as discipline."""
    _write(tmp_path, "m.py", DUR_STALE_CLEAN)
    _, findings = _analyze(tmp_path)
    assert _sc4(findings) == []


def test_staleness_non_mutating_reader_exempt(tmp_path):
    """A pure reader may compare epochs however it likes (the gang
    liveness probe uses exact-epoch equality legitimately)."""
    _write(tmp_path, "m.py", """
        def peek(self, msg, live):
            return msg.get("epoch") == live
    """)
    _, findings = _analyze(tmp_path)
    assert _sc4(findings) == []


DUR_JOURNAL_BAD = """
    RECORD_TYPES = ("done", "strike")

    def _journal_append(recs):
        pass

    def writer(recs):
        recs.append({"t": "done"})
        recs.append({"t": "orphan"})

    def replay(rec):
        t = rec.get("t")
        if t == "done":
            return 1
        if t == "ghost":
            return 2
        return 0
"""

DUR_JOURNAL_CLEAN = """
    RECORD_TYPES = ("done", "strike")

    def _journal_append(recs):
        pass

    def writer(recs):
        recs.append({"t": "done"})
        recs.append({"t": "strike"})

    def replay(rec):
        t = rec.get("t")
        if t in ("done", "strike"):
            return 1
        return 0
"""


def test_journal_round_trip_all_directions(tmp_path):
    _write(tmp_path, "j.py", DUR_JOURNAL_BAD)
    _, findings = _analyze(tmp_path)
    msgs = [f.message for f in findings if f.code == "SC404"]
    assert any("`orphan`" in m and "no" in m and "replay" in m
               for m in msgs)
    assert any("`orphan`" in m and "RECORD_TYPES" in m for m in msgs)
    assert any("`ghost`" in m and "nothing" in m for m in msgs)
    assert any("`strike`" in m and "declares" in m for m in msgs)


def test_journal_round_trip_clean(tmp_path):
    """Membership (`t in (...)`) arms count as replay coverage."""
    _write(tmp_path, "j.py", DUR_JOURNAL_CLEAN)
    _, findings = _analyze(tmp_path)
    assert _sc4(findings) == []


DUR_LOCK_BAD = """
    import threading

    MASTER_SERVICE = "scanner-master"
    RECORD_TYPES = ("done",)

    class RpcServer:
        def __init__(self, name, methods, port=0):
            pass

    class Master:
        def __init__(self):
            self._lock = threading.Lock()
            self._fence = threading.Event()

        def _journal_append(self, recs):
            if self._fence.is_set():
                return

        def _apply(self, rec):
            if rec.get("t") == "done":
                return 1

        def _rpc_get(self, req):
            return {}

        def flush_locked(self):
            with self._lock:
                self._journal_append([{"t": "done"}])

        def wait_locked(self):
            with self._lock:
                self._collective_digest_sum()

        def indirect(self):
            with self._lock:
                self._maybe_commit()

        def _maybe_commit(self):
            self._journal_append([])

        def serve(self):
            return RpcServer(MASTER_SERVICE, {
                "GetJob": self._rpc_get,
            })
"""

DUR_LOCK_CLEAN = DUR_LOCK_BAD.replace(
    """\
        def flush_locked(self):
            with self._lock:
                self._journal_append([{"t": "done"}])

        def wait_locked(self):
            with self._lock:
                self._collective_digest_sum()

        def indirect(self):
            with self._lock:
                self._maybe_commit()
""",
    """\
        def flush_locked(self):
            recs = [{"t": "done"}]
            with self._lock:
                staged = list(recs)
            self._journal_append(staged)

        def wait_locked(self):
            self._collective_digest_sum()

        def indirect(self):
            with self._lock:
                pass
            self._maybe_commit()
""")


def test_lock_across_commit_flagged(tmp_path):
    _write(tmp_path, "m.py", DUR_LOCK_BAD)
    _, findings = _analyze(tmp_path)
    msgs = [f.message for f in findings if f.code == "SC405"]
    assert len(msgs) == 3
    assert any("group-commit while holding" in m for m in msgs)
    assert any("collective wait" in m for m in msgs)
    assert any("_maybe_commit" in m and "transitively" in m
               for m in msgs)


def test_lock_released_before_commit_clean(tmp_path):
    _write(tmp_path, "m.py", DUR_LOCK_CLEAN)
    _, findings = _analyze(tmp_path)
    assert _sc4(findings) == []


def _sc406_repo(tmp_path, anchors, transitions, contracts=True):
    _write(tmp_path, "setup.py", "# root\n")
    if contracts:
        _write(tmp_path, "pkg/service.py", """
            RPC_CONTRACTS = {
                "FinishedWork": {"timeout_s": 1.0, "idempotent": False},
                "Ping": {"timeout_s": 1.0, "idempotent": True},
            }
        """)
    body = "RPC_ANCHORS = {\n"
    for k, v in anchors.items():
        body += f'    "{k}": "{v}",\n'
    body += "}\n\n"
    for t in transitions:
        body += f"def t_{t}(s):\n    return s\n\n"
    _write(tmp_path, "pkg/analysis/model/protocol.py", body)
    return _analyze(tmp_path, "pkg")[1]


def test_model_anchoring_clean(tmp_path):
    findings = _sc406_repo(tmp_path,
                           {"finished_work": "FinishedWork"},
                           ["finished_work"])
    assert [f for f in findings if f.code == "SC406"] == []


def test_model_anchor_without_transition_flagged(tmp_path):
    findings = _sc406_repo(tmp_path,
                           {"finished_work": "FinishedWork",
                            "ghost": "Ping"},
                           ["finished_work"])
    msgs = [f.message for f in findings if f.code == "SC406"]
    assert any("`ghost`" in m and "t_ghost" in m for m in msgs)


def test_model_anchor_without_contract_flagged(tmp_path):
    findings = _sc406_repo(tmp_path,
                           {"finished_work": "FinishedWork",
                            "extra": "NoSuchRpc"},
                           ["finished_work", "extra"])
    msgs = [f.message for f in findings if f.code == "SC406"]
    assert any("NoSuchRpc" in m and "no RPC_CONTRACTS entry" in m
               for m in msgs)


def test_model_missing_nonidempotent_rpc_flagged(tmp_path):
    """Drift the OTHER direction: an idempotent=False contract with no
    anchoring transition blinds the explorer to a mutating RPC."""
    findings = _sc406_repo(tmp_path,
                           {"ping": "Ping"},
                           ["ping"])
    msgs = [f.message for f in findings if f.code == "SC406"]
    assert any("FinishedWork" in m and "idempotent=False" in m
               for m in msgs)


def test_model_package_without_anchors_flagged(tmp_path):
    _write(tmp_path, "setup.py", "# root\n")
    _write(tmp_path, "pkg/service.py", """
        RPC_CONTRACTS = {
            "FinishedWork": {"timeout_s": 1.0, "idempotent": False},
        }
    """)
    _write(tmp_path, "pkg/analysis/model/explorer.py", "x = 1\n")
    _, findings = _analyze(tmp_path, "pkg")
    msgs = [f.message for f in findings if f.code == "SC406"]
    assert any("no RPC_ANCHORS" in m for m in msgs)


def test_real_model_anchoring_is_live():
    """The shipped analysis/model/protocol.py stays pinned to the
    shipped RPC_CONTRACTS: SC406 must fire if either side drifts."""
    from scanner_tpu.analysis.model import RPC_ANCHORS
    from scanner_tpu.engine.service import RPC_CONTRACTS
    non_idem = {r for r, c in RPC_CONTRACTS.items()
                if c.get("idempotent") is False}
    anchored = set(RPC_ANCHORS.values())
    assert non_idem <= anchored
    assert anchored <= set(RPC_CONTRACTS)
    # and the analyzer agrees (zero SC406 over the real tree)
    findings = run_analysis([os.path.join(REPO, "scanner_tpu")],
                            root=REPO, select=["SC406"])
    assert findings == []


# ---------------------------------------------------------------------------
# baseline hygiene: duplicate fingerprints
# ---------------------------------------------------------------------------

def test_baseline_rejects_duplicate_fingerprints(tmp_path):
    """A copy-pasted baseline entry silently double-counts an accepted
    exception — the loader must refuse the file outright."""
    _write(tmp_path, "s.py", SLEEPY)
    proj, findings = _analyze(tmp_path)
    res = split_findings(proj, findings)
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, res.unsuppressed)
    doc = json.load(open(bl_path))
    doc["entries"][0]["justification"] = "legit entry"
    doc["entries"].append(dict(doc["entries"][0]))
    json.dump(doc, open(bl_path, "w"))
    with pytest.raises(BaselineError) as ei:
        load_baseline(bl_path)
    assert "duplicate fingerprint" in str(ei.value)


# ---------------------------------------------------------------------------
# --changed: restricted runs agree with full runs
# ---------------------------------------------------------------------------

def _git(cwd, *args):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   capture_output=True, text=True, timeout=60)


def _changed_repo(tmp_path):
    _write(tmp_path, "setup.py", "# root\n")
    _write(tmp_path, "scanner_tpu/__init__.py", "")
    _write(tmp_path, "scanner_tpu/mod.py", "x = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-q", "--allow-empty", "-m", "seed")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-q", "-m", "clean tree")
    return tmp_path


def _run_check(root, *extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanner_check.py"),
         "--root", str(root), str(root / "scanner_tpu"),
         "--no-baseline", "--json", *extra],
        capture_output=True, text=True, env=env, timeout=120)


def test_changed_agrees_with_full_run(tmp_path):
    """--changed over a dirty checkout reports exactly the findings a
    full run reports for the touched modules."""
    root = _changed_repo(tmp_path)
    _write(root, "scanner_tpu/mod.py", SLEEPY)
    full = json.loads(_run_check(root).stdout)
    restricted = json.loads(_run_check(root, "--changed").stdout)
    assert restricted["counts"] == full["counts"] == {"SC202": 1}
    strip = [(f["code"], f["path"], f["fingerprint"])
             for f in full["findings"]]
    strip_r = [(f["code"], f["path"], f["fingerprint"])
               for f in restricted["findings"]]
    assert strip == strip_r


def test_changed_clean_tree_is_noop(tmp_path):
    root = _changed_repo(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanner_check.py"),
         "--root", str(root), str(root / "scanner_tpu"),
         "--no-baseline", "--changed"],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0
    assert "no scanner_tpu modules touched" in r.stdout


def test_changed_refuses_write_baseline(tmp_path):
    root = _changed_repo(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanner_check.py"),
         "--root", str(root), "--changed", "--write-baseline"],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 2 and "erase" in r.stderr


def test_changed_paths_fall_back_when_analyzer_touched(tmp_path):
    """A change under scanner_tpu/analysis/ affects every finding, so
    the restriction must dissolve into a full run."""
    from scanner_tpu.analysis.static import changed_paths
    root = _changed_repo(tmp_path)
    _write(root, "scanner_tpu/analysis/static/extra.py", "y = 2\n")
    assert changed_paths(str(root)) is None
