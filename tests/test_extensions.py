"""Pluggable sources/sinks, image ingest, config, load_op, batch_load."""

import os
import struct

import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
from scanner_tpu.storage import FilesStream
import scanner_tpu.kernels
from scanner_tpu import video as scv


@pytest.fixture(scope="module")
def sc(tmp_path_factory):
    root = tmp_path_factory.mktemp("ext")
    vid = str(root / "v.mp4")
    scv.synthesize_video(vid, num_frames=24, width=64, height=48, fps=24)
    client = Client(db_path=str(root / "db"))
    client.ingest_videos([("test1", vid)])
    yield client, str(root)
    client.stop()


def test_files_source_and_sink(sc):
    client, root = sc
    # write input rows as files
    src_dir = os.path.join(root, "files_in")
    os.makedirs(os.path.join(src_dir, "nums"))
    for i in range(10):
        with open(os.path.join(src_dir, "nums", f"{i:08d}.bin"), "wb") as f:
            f.write(struct.pack("<q", i * 3))
    in_stream = FilesStream("nums", src_dir)
    assert in_stream.len() == 10

    import scanner_tpu
    from typing import Any

    @scanner_tpu.register_op(name="TripleUp")
    class TripleUp(scanner_tpu.Kernel):
        def execute(self, x: bytes) -> bytes:
            (v,) = struct.unpack("<q", x)
            return struct.pack("<q", v + 1)

    data = client.io.Input([in_stream])
    up = client.ops.TripleUp(x=data)
    out_stream = FilesStream("nums_out", os.path.join(root, "files_out"))
    client.run(client.io.Output(up, [out_stream]), PerfParams.manual(4, 4),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    got = [struct.unpack("<q", b)[0] for b in out_stream.load()]
    assert got == [i * 3 + 1 for i in range(10)]


def test_files_to_table_and_back(sc):
    client, root = sc
    # video input -> files sink of pickled histograms
    frame = client.io.Input([NamedVideoStream(client, "test1")])
    hist = client.ops.Histogram(frame=frame)
    out = FilesStream("hists", os.path.join(root, "files_out2"),
                      codec="pickle")
    client.run(client.io.Output(hist, [out]), PerfParams.manual(8, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == 24 and rows[0][0].shape == (16,)


def test_image_ingest_and_pipeline(sc, tmp_path):
    client, root = sc
    from PIL import Image
    paths = []
    for i in range(5):
        p = str(tmp_path / f"img{i}.png")
        Image.fromarray(scv.frame_pattern(i, 48, 64)).save(p)
        paths.append(p)
    client.ingest_images("stills", paths)
    t = client.table("stills")
    assert t.num_rows() == 5
    # through the engine
    frame = client.io.Input([NamedVideoStream(client, "stills")])
    hist = client.ops.Histogram(frame=frame)
    out = NamedStream(client, "still_hists")
    client.run(client.io.Output(hist, [out]), PerfParams.manual(4, 4),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == 5
    assert int(rows[0][0].sum()) == 64 * 48
    # encode kernel roundtrip
    frame = client.io.Input([NamedVideoStream(client, "stills")])
    enc = client.ops.ImageEncode(frame=frame, format="png")
    out2 = NamedStream(client, "still_pngs")
    client.run(client.io.Output(enc, [out2]), PerfParams.manual(4, 4),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    blobs = list(out2.load())
    assert blobs[0][:8] == b"\x89PNG\r\n\x1a\n"


def test_config_roundtrip(tmp_path):
    from scanner_tpu.config import Config, default_config, dump_toml
    p = str(tmp_path / "cfg.toml")
    with open(p, "w") as f:
        f.write(dump_toml(default_config()))
    cfg = Config(p, db_path=str(tmp_path / "db"))
    assert cfg.storage_type == "posix"
    assert cfg.db_path == str(tmp_path / "db")
    assert cfg.master_address is None  # default: in-process execution
    # explicit master in config selects cluster mode, localhost included
    with open(p, "w") as f:
        f.write('[network]\nmaster = "localhost"\nmaster_port = 5055\n')
    cfg = Config(p)
    assert cfg.master_address == "localhost:5055"
    # legacy combined key also accepted
    with open(p, "w") as f:
        f.write('[network]\nmaster_address = "10.0.0.5:5000"\n')
    assert Config(p).master_address == "10.0.0.5:5000"


def test_load_op(sc, tmp_path):
    client, root = sc
    mod = tmp_path / "user_ops.py"
    mod.write_text(
        "from scanner_tpu import Kernel, register_op\n"
        "@register_op(name='UserDouble')\n"
        "class UserDouble(Kernel):\n"
        "    def execute(self, x: bytes) -> bytes:\n"
        "        return x + x\n")
    client.load_op(str(mod))
    from scanner_tpu.graph.ops import registry
    assert registry.has("UserDouble")


def test_batch_load(sc):
    client, root = sc
    client.new_table("bl1", ["c"], [[b"a"], [b"b"]], overwrite=True)
    client.new_table("bl2", ["c"], [[b"x"]], overwrite=True)
    s1, s2 = NamedStream(client, "bl1"), NamedStream(client, "bl2")
    res = client.batch_load([s1, s2])
    assert res == [[b"a", b"b"], [b"x"]]


def test_deploy_manifests():
    from scanner_tpu.deploy import (CloudConfig, Cluster, ClusterConfig,
                                    MachineType)
    cfg = ClusterConfig(id="sc", num_workers=4,
                        worker=MachineType(tpu_type="v5litepod-4"))
    cluster = Cluster(CloudConfig(project="p"), cfg)
    by_kind = {(m["kind"], m["metadata"]["name"]): m
               for m in cluster.manifests()}
    assert ("Deployment", "sc-master") in by_kind
    assert ("ConfigMap", "sc-config") in by_kind
    workers = by_kind[("StatefulSet", "sc-worker")]
    assert workers["spec"]["replicas"] == 4  # single-host slice: 1 pod each
    limits = workers["spec"]["template"]["spec"]["containers"][0][
        "resources"]["limits"]
    assert limits["google.com/tpu"] == "4"
    # SIGTERM drain window (Worker.drain, docs/robustness.md): pods get
    # the configured grace period before the SIGKILL follow-up
    assert workers["spec"]["template"]["spec"][
        "terminationGracePeriodSeconds"] == cfg.termination_grace_period
    assert cfg.price_per_hour() > 0
    assert "sc-master" in cluster.manifests_json()
    toml = by_kind[("ConfigMap", "sc-config")]["data"]["scanner_tpu.toml"]
    assert 'type = "posix"' in toml


def test_deploy_multihost_slice():
    """A v5litepod-8 slice spans 2 hosts: each slice is its OWN
    StatefulSet pinned to a dedicated per-slice node pool (nodeSelector
    gke-nodepool + gke-tpu-topology) so a jax.distributed coordinator
    group is guaranteed slice-coherent; in-slice rank = pod ordinal,
    coordinator at pod 0's headless-service DNS name."""
    import ast

    from scanner_tpu.deploy import (CloudConfig, Cluster, ClusterConfig,
                                    MachineType, tpu_hosts)
    assert tpu_hosts("v5litepod-8") == 2
    cfg = ClusterConfig(id="sc", num_workers=3,
                        worker=MachineType(tpu_type="v5litepod-8"),
                        db_path="gs://bkt/db")
    cluster = Cluster(CloudConfig(project="p"), cfg)
    by_kind = {(m["kind"], m["metadata"]["name"]): m
               for m in cluster.manifests()}
    for i in range(3):
        workers = by_kind[("StatefulSet", f"sc-worker-s{i}")]
        assert workers["spec"]["replicas"] == 2   # hosts per slice
        pod = workers["spec"]["template"]["spec"]
        # slice coherence: dedicated pool + declared physical topology
        assert pod["nodeSelector"]["cloud.google.com/gke-nodepool"] \
            == f"sc-tpu-{i}"
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] \
            == "2x4"
        payload = pod["containers"][0]["command"][2]
        ast.parse(payload)  # generated -c program must be valid python
        assert "num_processes=2" in payload
        assert f"sc-worker-s{i}-0.sc-workers:8476" in payload
        # in-slice rank comes straight from the pod ordinal
        assert "rsplit('-', 1)[1]" in payload
    # headless service for stable pod DNS
    svc = by_kind[("Service", "sc-workers")]
    assert svc["spec"]["clusterIP"] == "None"
    # gs:// db selects the gcs backend in the ConfigMap
    toml = by_kind[("ConfigMap", "sc-config")]["data"]["scanner_tpu.toml"]
    assert 'type = "gcs"' in toml


def test_deploy_metrics_port_wiring():
    """ClusterConfig.metrics_port threads the live-telemetry endpoint
    through the manifests: start_master/start_worker args, exposed
    container ports, and the ConfigMap toml — and stays fully absent at
    the default (telemetry serving is opt-in, docs/observability.md)."""
    import ast

    from scanner_tpu.deploy import (CloudConfig, Cluster, ClusterConfig,
                                    MachineType)

    def manifests(port):
        cfg = ClusterConfig(id="sc", num_workers=2,
                            worker=MachineType(tpu_type="v5litepod-4"),
                            metrics_port=port)
        cluster = Cluster(CloudConfig(project="p"), cfg)
        return {(m["kind"], m["metadata"]["name"]): m
                for m in cluster.manifests()}

    on = manifests(9090)
    mc = on[("Deployment", "sc-master")]["spec"]["template"]["spec"][
        "containers"][0]
    ast.parse(mc["command"][2])
    assert "metrics_port=9090" in mc["command"][2]
    assert {"containerPort": 9090, "name": "metrics"} in mc["ports"]
    wc = on[("StatefulSet", "sc-worker")]["spec"]["template"]["spec"][
        "containers"][0]
    ast.parse(wc["command"][2])
    assert "metrics_port=9090" in wc["command"][2]
    assert {"containerPort": 9090, "name": "metrics"} in wc["ports"]
    # workers advertise their stable pod DNS so the master's GetMetrics
    # aggregation can dial them cross-host
    assert "advertise_host=os.environ['POD_NAME'] + '.sc-workers'" \
        in wc["command"][2]
    assert "metrics_port = 9090" in on[("ConfigMap", "sc-config")][
        "data"]["scanner_tpu.toml"]

    off = manifests(0)
    mc = off[("Deployment", "sc-master")]["spec"]["template"]["spec"][
        "containers"][0]
    assert "metrics_port" not in mc["command"][2]
    wc = off[("StatefulSet", "sc-worker")]["spec"]["template"]["spec"][
        "containers"][0]
    assert "metrics_port" not in wc["command"][2]
    assert "ports" not in wc


def test_deploy_compilation_cache_wiring(tmp_path, monkeypatch):
    """ClusterConfig.compilation_cache_dir threads JAX's persistent
    compilation cache through the manifests (ConfigMap [perf] section +
    worker env var) and stays fully absent at the default; the config
    knob and jaxenv helper resolve the same setting process-side."""
    from scanner_tpu.deploy import (CloudConfig, Cluster, ClusterConfig,
                                    MachineType)

    def manifests(cache):
        cfg = ClusterConfig(id="sc", num_workers=2,
                            worker=MachineType(tpu_type="v5litepod-4"),
                            compilation_cache_dir=cache)
        return {(m["kind"], m["metadata"]["name"]): m
                for m in Cluster(CloudConfig(project="p"), cfg).manifests()}

    on = manifests("gs://bkt/xla-cache")
    toml = on[("ConfigMap", "sc-config")]["data"]["scanner_tpu.toml"]
    assert "[perf]" in toml
    assert 'compilation_cache_dir = "gs://bkt/xla-cache"' in toml
    wc = on[("StatefulSet", "sc-worker")]["spec"]["template"]["spec"][
        "containers"][0]
    assert {"name": "SCANNER_TPU_COMPILATION_CACHE",
            "value": "gs://bkt/xla-cache"} in wc["env"]

    off = manifests("")
    assert "[perf]" not in off[("ConfigMap", "sc-config")]["data"][
        "scanner_tpu.toml"]
    wc = off[("StatefulSet", "sc-worker")]["spec"]["template"]["spec"][
        "containers"][0]
    assert not any(e.get("name") == "SCANNER_TPU_COMPILATION_CACHE"
                   for e in wc["env"])

    # config knob -> Config property
    from scanner_tpu.config import Config, dump_toml
    p = tmp_path / "cfg.toml"
    p.write_text(dump_toml(
        {"perf": {"compilation_cache_dir": str(tmp_path / "cc")}}))
    assert Config(str(p)).compilation_cache_dir == str(tmp_path / "cc")
    p.write_text(dump_toml({"perf": {"compilation_cache_dir": ""}}))
    assert Config(str(p)).compilation_cache_dir is None

    # jaxenv helper: env-var fallback, creates the dir, points jax at it
    import jax

    from scanner_tpu.util.jaxenv import enable_compilation_cache
    monkeypatch.delenv("SCANNER_TPU_COMPILATION_CACHE", raising=False)
    assert enable_compilation_cache(None) is None  # unset = no-op
    cache = tmp_path / "xla"
    monkeypatch.setenv("SCANNER_TPU_COMPILATION_CACHE", str(cache))
    assert enable_compilation_cache(None) == str(cache)
    assert cache.is_dir()
    assert jax.config.jax_compilation_cache_dir == str(cache)
    jax.config.update("jax_compilation_cache_dir", None)  # detach again


def test_deploy_gcloud_commands():
    from scanner_tpu.deploy import (CloudConfig, Cluster, ClusterConfig,
                                    MachineType)
    cfg = ClusterConfig(id="sc", num_workers=2,
                        worker=MachineType(tpu_type="v5litepod-8",
                                           spot=True),
                        autoscale=True)
    cluster = Cluster(CloudConfig(project="proj", zone="us-east5-a"), cfg)
    cmds = cluster.create_commands()
    assert cmds[0][:3] == ["gcloud", "container", "--project"]
    # multi-host + autoscale: one pool PER candidate slice (autoscale cap
    # = 2x num_workers), each 0..hosts nodes
    pools = cmds[1:]
    assert len(pools) == 4
    for i, pool in enumerate(pools):
        assert "node-pools" in pool and "--spot" in pool
        assert pool[pool.index("create") + 1] == f"sc-tpu-{i}"
        assert "--enable-autoscaling" in pool
        # active slices start full; surplus autoscale pools start empty
        want_nodes = "2" if i < 2 else "0"
        assert pool[pool.index("--num-nodes") + 1] == want_nodes
        assert "ct5lp-hightpu-4t" in pool
        # GKE needs the physical slice topology
        assert pool[pool.index("--tpu-topology") + 1] == "2x4"
        assert pool[pool.index("--max-nodes") + 1] == "2"
    from scanner_tpu.deploy import cluster_resize_commands
    # autoscale: pools pre-exist and follow their pods — no gcloud needed
    assert cluster_resize_commands(cluster.cloud, cfg, 3) == []
    # non-autoscale multi-host: slice-granular pool create/delete
    cfg2 = ClusterConfig(id="sc", num_workers=2,
                         worker=MachineType(tpu_type="v5litepod-8"))
    grow = cluster_resize_commands(cluster.cloud, cfg2, 3)
    assert len(grow) == 1 and "sc-tpu-2" in grow[0]
    shrink = cluster_resize_commands(cluster.cloud, cfg2, 1)
    assert len(shrink) == 1 and "delete" in shrink[0] \
        and "sc-tpu-1" in shrink[0]
    dele = cluster.delete_commands()[0]
    assert "delete" in dele and "sc" in dele
    # spot pricing discounts
    assert MachineType(tpu_type="v5litepod-8", spot=True).price_per_hour() \
        < MachineType(tpu_type="v5litepod-8").price_per_hour()
