"""Remediation controller suite (engine/controller.py + the service
wiring): synthetic-clock playbook units (cooldown, hysteresis,
dry-run, rate limit, audit), autoscaler A/B under synthetic
saturation, the preemption-notice assignment fence, admission pause,
scale-down-never-kills-in-flight, and the headline chaos e2e —
preempt ~30% of workers mid-bulk under load, output bit-exact,
requeues strike-free, no `unhealthy` roll-up page after rule
hold-down (docs/robustness.md §Remediation playbooks)."""

import collections
import struct
import sys
import threading
import time

import cloudpickle
import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         PerfParams, register_op)
from scanner_tpu.engine import controller as ctl
from scanner_tpu.engine.service import Master, Worker, _BulkJob
from scanner_tpu.util import faults
from scanner_tpu.util import health as _health
from scanner_tpu.util import metrics as _mx
from scanner_tpu.util import retry as _retry

# test kernels travel inside the job spec
cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.chaos

N_ROWS = 48


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="CtlSlowDouble")
class CtlSlowDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        time.sleep(0.25)
        return _pk(2 * struct.unpack("<q", x)[0])


EXPECT = [_pk(2 * (100 + i)) for i in range(N_ROWS)]


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    total = 0.0
    for s in entry.get("samples", []):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# playbook units (private controller, synthetic clock)
# ---------------------------------------------------------------------------

def _mk(playbooks, t0=1000.0):
    clock = [t0]
    c = ctl.RemediationController(playbooks=playbooks,
                                  clock=lambda: clock[0])
    return c, clock


def _fire(rule, **labels):
    return {"state": "firing", "rule": rule, "severity": "warning",
            "labels": labels, "value": 1.0}


def test_playbook_cooldown_is_per_label_group():
    pb = ctl.Playbook(name="p", alert="hbm_pressure", action="act",
                      cooldown_s=10.0, max_per_window=100)
    c, clock = _mk([pb])
    calls = []
    c.register_action("act", lambda t: calls.append(t["labels"]))
    c.on_transition(_fire("hbm_pressure", device="tpu:0"))
    c.on_transition(_fire("hbm_pressure", device="tpu:0"))  # cooldown
    # a DIFFERENT chip is not blocked by tpu:0's cooldown
    c.on_transition(_fire("hbm_pressure", device="tpu:1"))
    assert calls == [{"device": "tpu:0"}, {"device": "tpu:1"}]
    outcomes = [a["outcome"] for a in c.audit()]
    assert outcomes == ["applied", "cooldown", "applied"]
    # past the cooldown the same chip acts again
    clock[0] += 11.0
    c.on_transition(_fire("hbm_pressure", device="tpu:0"))
    assert len(calls) == 3


def test_playbook_hysteresis_holds_and_refire_cancels():
    pb = ctl.Playbook(name="p", alert="stage_backpressure",
                      action="on", resolve_action="off",
                      cooldown_s=0.0, hysteresis_s=5.0)
    c, clock = _mk([pb])
    calls = []
    c.register_action("on", lambda t: calls.append("on"))
    c.register_action("off", lambda t: calls.append("off"))
    c.on_transition(_fire("stage_backpressure", stage="save"))
    c.on_transition(dict(_fire("stage_backpressure", stage="save"),
                         state="resolved"))
    c.tick()                       # hold not elapsed
    assert calls == ["on"]
    clock[0] += 3.0
    # alert re-fires inside the hold: the pending resolve is cancelled
    c.on_transition(_fire("stage_backpressure", stage="save"))
    clock[0] += 10.0
    c.tick()
    assert "off" not in calls
    c.on_transition(dict(_fire("stage_backpressure", stage="save"),
                         state="resolved"))
    clock[0] += 6.0
    c.tick()
    assert calls[-1] == "off"


def test_playbook_rate_limit_and_unbound_and_error():
    pb = ctl.Playbook(name="p", alert="recompile_storm", action="act",
                      cooldown_s=0.0, max_per_window=2, window_s=60.0)
    c, clock = _mk([pb])
    # unbound: no action registered yet
    c.on_transition(_fire("recompile_storm"))
    assert c.audit()[-1]["outcome"] == "unbound"

    n = [0]

    def act(t):
        n[0] += 1
        if n[0] == 2:
            raise RuntimeError("boom")
        return f"ok{n[0]}"

    c.register_action("act", act)
    c.on_transition(_fire("recompile_storm"))          # applied
    c.on_transition(_fire("recompile_storm"))          # applied -> error
    c.on_transition(_fire("recompile_storm"))          # rate limited
    outcomes = [a["outcome"] for a in c.audit()]
    assert outcomes == ["unbound", "applied", "error", "rate_limited"]
    assert c.audit()[1]["detail"] == "ok1"
    assert "boom" in c.audit()[2]["detail"]
    # the window slides: actions return after it passes
    clock[0] += 61.0
    c.on_transition(_fire("recompile_storm"))
    assert c.audit()[-1]["outcome"] == "applied"


def test_playbook_dry_run_audits_without_invoking(monkeypatch):
    pb = ctl.Playbook(name="p", alert="hbm_pressure", action="act",
                      cooldown_s=30.0)
    c, _clock = _mk([pb])
    calls = []
    c.register_action("act", lambda t: calls.append(1))
    monkeypatch.setattr(ctl, "_DRY_RUN", True)
    c.on_transition(_fire("hbm_pressure", device="tpu:0"))
    assert calls == []
    assert c.audit()[-1]["outcome"] == "dry_run"
    assert _counter("scanner_tpu_remediations_total", playbook="p",
                    action="act", outcome="dry_run") >= 1
    # dry-run records gate state: the staging decision sequence must
    # match production's (dry_run then cooldown, not dry_run forever)
    c.on_transition(_fire("hbm_pressure", device="tpu:0"))
    assert c.audit()[-1]["outcome"] == "cooldown"
    assert calls == []


def test_resolve_waits_for_every_label_group():
    """One stage recovering must not resume admission while another is
    still backpressured: the resolve reversal runs only once EVERY
    firing label-group of the alert has resolved."""
    pb = ctl.Playbook(name="p", alert="stage_backpressure",
                      action="on", resolve_action="off",
                      cooldown_s=0.0, hysteresis_s=0.0)
    c, _clock = _mk([pb])
    calls = []
    c.register_action("on", lambda t: calls.append("on"))
    c.register_action("off", lambda t: calls.append("off"))
    c.on_transition(_fire("stage_backpressure", stage="load"))
    c.on_transition(_fire("stage_backpressure", stage="save"))
    c.on_transition(dict(_fire("stage_backpressure", stage="load"),
                         state="resolved"))
    assert "off" not in calls          # save still fires
    c.on_transition(dict(_fire("stage_backpressure", stage="save"),
                         state="resolved"))
    assert calls[-1] == "off"


def test_autoscaler_rolls_back_desired_on_actuator_failure():
    """A failed actuation (transient k8s API error) must not latch the
    new desired count — later observations keep retrying, paced by the
    cooldown, until the actuator succeeds."""
    clock = [9000.0]
    boom = [True]
    applied = []

    def actuator(n):
        if boom[0]:
            raise RuntimeError("kubectl down")
        applied.append(n)

    ctrl = ctl.RemediationController(playbooks=[],
                                     clock=lambda: clock[0])
    a = ctl.Autoscaler(
        ctl.AutoscaleConfig(min_replicas=1, max_replicas=4,
                            queue_per_worker=2.0, up_cooldown_s=10.0),
        actuator=actuator, controller=ctrl, clock=lambda: clock[0])
    assert a.observe(workers=1, queued=8, outstanding=0) is None
    assert ctrl.audit()[-1]["outcome"] == "error"
    assert a.desired() == 1            # rolled back, not latched
    # after the cooldown the same signal retries and succeeds
    boom[0] = False
    clock[0] += 11.0
    assert a.observe(workers=1, queued=8, outstanding=0) == 4
    assert applied == [4] and a.desired() == 4


def test_unregister_action_is_owner_checked():
    c, _clock = _mk([])
    old = lambda t: "old"      # noqa: E731
    new = lambda t: "new"      # noqa: E731
    c.register_action("act", old)
    c.register_action("act", new)      # latest wins
    c.unregister_action("act", owner=old)   # stale owner: no-op
    with c._lock:
        assert c._actions.get("act") is new
    c.unregister_action("act", owner=new)
    with c._lock:
        assert "act" not in c._actions


def test_master_stop_clears_pause_gauge_and_keeps_sibling(tmp_path):
    """A master stopped while admission is paused must reset the
    process-wide gauge/gate, and its stop must not strip a newer
    same-process master's action bindings."""
    a = Master(db_path=str(tmp_path / "a"), no_workers_timeout=30.0)
    b = Master(db_path=str(tmp_path / "b"), no_workers_timeout=30.0)
    a._pause_admission(_fire("stage_backpressure"))
    assert _counter("scanner_tpu_master_admission_paused") == 1
    a.stop()
    assert _counter("scanner_tpu_master_admission_paused") == 0
    # b's bindings (latest registration) survived a's stop
    with ctl.controller()._lock:
        cur = ctl.controller()._actions.get("pause_admission")
    assert cur == b._pause_admission
    b.stop()


def test_disabled_controller_is_signal_only(monkeypatch):
    pb = ctl.Playbook(name="p", alert="hbm_pressure", action="act",
                      cooldown_s=0.0)
    c, _clock = _mk([pb])
    calls = []
    c.register_action("act", lambda t: calls.append(1))
    monkeypatch.setattr(ctl, "_ENABLED", False)
    c.on_transition(_fire("hbm_pressure", device="tpu:0"))
    c.tick()
    assert calls == [] and c.audit() == []
    assert ctl.ensure_started() is None


def test_default_playbooks_bind_known_alerts():
    rules = {r.name for r in _health.DEFAULT_RULES}
    for pb in ctl.DEFAULT_PLAYBOOKS:
        assert pb.alert in rules, pb.name


def test_ladder_rewarm_action_through_playbook(monkeypatch):
    from scanner_tpu.engine import evaluate as _evaluate
    monkeypatch.setattr(_evaluate, "rewarm_all", lambda: 3)
    c, _clock = _mk([p for p in ctl.default_playbooks()
                     if p.name == "ladder_rewarm"])
    c.register_action("rewarm_ladders", ctl._rewarm_ladders)
    c.on_transition(_fire("recompile_storm"))
    entry = c.audit()[-1]
    assert entry["outcome"] == "applied"
    assert entry["detail"] == "rewarmed 3 kernel ladder(s)"


def test_rewarm_all_empty_registry_is_zero():
    # no live evaluators in this moment -> 0, never an exception
    from scanner_tpu.engine import evaluate as _evaluate
    assert isinstance(_evaluate.rewarm_all(), int)


# ---------------------------------------------------------------------------
# autoscaler units (synthetic clock, callback actuator)
# ---------------------------------------------------------------------------

def _mk_autoscaler(**cfg_kw):
    clock = [5000.0]
    scaled = []
    cfg = ctl.AutoscaleConfig(**cfg_kw)
    ctrl = ctl.RemediationController(playbooks=[],
                                     clock=lambda: clock[0])
    a = ctl.Autoscaler(cfg, actuator=scaled.append, controller=ctrl,
                       clock=lambda: clock[0])
    return a, clock, scaled, ctrl


def test_autoscaler_converges_within_bounds_with_cooldowns():
    a, clock, scaled, _c = _mk_autoscaler(
        min_replicas=1, max_replicas=4, queue_per_worker=2.0,
        up_cooldown_s=10.0, down_cooldown_s=10.0, idle_grace_s=5.0)
    # synthetic saturation + deep backlog: wants 4 (clamped from 5+)
    assert a.observe(workers=1, queued=10, outstanding=2,
                     saturated_workers=1) == 4
    assert scaled == [4]
    # cooldown: an immediate second up-signal does nothing
    assert a.observe(workers=1, queued=20, outstanding=0,
                     saturated_workers=1) is None
    assert scaled == [4]
    # the clamp holds whatever the backlog says
    clock[0] += 11.0
    assert a.observe(workers=4, queued=100, outstanding=0,
                     saturated_workers=4) is None  # already at max
    assert a.desired() == 4


def test_autoscaler_scales_down_one_step_only_when_idle():
    a, clock, scaled, _c = _mk_autoscaler(
        min_replicas=1, max_replicas=4, queue_per_worker=2.0,
        up_cooldown_s=0.0, down_cooldown_s=0.0, idle_grace_s=5.0)
    a.observe(workers=1, queued=8, outstanding=0)     # up to 4
    assert a.desired() == 4
    # work still queued/outstanding: NEVER scales down
    a.observe(workers=4, queued=0, outstanding=1)
    clock[0] += 100.0
    a.observe(workers=4, queued=0, outstanding=1)
    assert a.desired() == 4
    # idle, but the grace period must elapse first
    a.observe(workers=4, queued=0, outstanding=0)
    assert a.desired() == 4
    clock[0] += 6.0
    a.observe(workers=4, queued=0, outstanding=0)
    assert a.desired() == 3 and scaled[-1] == 3
    # one step at a time, re-armed only after another full grace
    a.observe(workers=3, queued=0, outstanding=0)
    assert a.desired() == 3
    clock[0] += 6.0
    a.observe(workers=3, queued=0, outstanding=0)
    assert a.desired() == 2
    # never below min
    for _ in range(5):
        clock[0] += 6.0
        a.observe(workers=2, queued=0, outstanding=0)
    assert a.desired() == 1


def test_autoscaler_dry_run_and_unbound(monkeypatch):
    a, _clock, scaled, c = _mk_autoscaler(
        min_replicas=1, max_replicas=4, queue_per_worker=1.0,
        up_cooldown_s=0.0)
    monkeypatch.setattr(ctl, "_DRY_RUN", True)
    assert a.observe(workers=1, queued=4, outstanding=0) == 4
    assert scaled == []
    assert c.audit()[-1]["outcome"] == "dry_run"
    monkeypatch.setattr(ctl, "_DRY_RUN", False)
    a2 = ctl.Autoscaler(ctl.AutoscaleConfig(max_replicas=4,
                                            up_cooldown_s=0.0),
                        actuator=None, controller=c,
                        clock=lambda: 1.0)
    assert a2.observe(workers=1, queued=40, outstanding=0) == 4
    assert c.audit()[-1]["outcome"] == "unbound"


def test_autoscaler_disabled_is_inert(monkeypatch):
    a, _clock, scaled, c = _mk_autoscaler(up_cooldown_s=0.0)
    monkeypatch.setattr(ctl, "_ENABLED", False)
    assert a.observe(workers=1, queued=100, outstanding=0) is None
    assert scaled == [] and c.audit() == []


# ---------------------------------------------------------------------------
# retry budget (util/retry.py)
# ---------------------------------------------------------------------------

def test_retry_budget_floor_and_deposit():
    b = _retry.RetryBudget(max_tokens=4.0, token_ratio=1.0)
    assert b.take() and b.take()
    assert not b.take()            # at the floor (max/2)
    b.on_success()
    assert b.take()
    b.reset()
    assert b.tokens() == 4.0


def test_call_with_backoff_respects_budget():
    b = _retry.RetryBudget(max_tokens=4.0, token_ratio=1.0)
    calls = [0]

    def flaky():
        calls[0] += 1
        raise ConnectionError("down")

    before = _counter("scanner_tpu_retry_budget_exhausted_total")
    with pytest.raises(ConnectionError):
        _retry.call_with_backoff(
            flaky, is_transient=lambda e: True, retries=10,
            base=0.0001, cap=0.001, budget=b, label="unit")
    # 2 retries allowed (tokens 4 -> floor 2), then fail-fast
    assert calls[0] == 3
    assert _counter("scanner_tpu_retry_budget_exhausted_total",
                    site="unit") >= 1
    assert _counter("scanner_tpu_retry_budget_exhausted_total") > before
    # successes refill: the shared-path deposit happens on return
    b.on_success()
    assert _retry.call_with_backoff(
        lambda: 7, is_transient=lambda e: True, budget=b) == 7


# ---------------------------------------------------------------------------
# master wiring units (no pipeline)
# ---------------------------------------------------------------------------

@pytest.fixture()
def bare_master(tmp_path):
    m = Master(db_path=str(tmp_path / "db"), no_workers_timeout=30.0)
    yield m
    m.stop()


def test_admission_pause_gates_new_job_and_resumes(bare_master):
    m = bare_master
    m._pause_admission(_fire("stage_backpressure", source="workers"))
    reply = m._rpc_new_job({"spec": b"irrelevant"})
    assert reply.get("admission_paused") is True
    assert "admission paused" in reply["error"]
    assert reply.get("retry_after")
    assert _counter("scanner_tpu_master_admission_paused") == 1
    m._resume_admission({})
    assert m._admission_paused is None
    assert _counter("scanner_tpu_master_admission_paused") == 0


def test_worker_alert_fold_drives_admission_playbook(bare_master):
    """A worker-side stage_backpressure alert (heartbeat `firing`
    field) reaches the master's admission gate through the scan-loop
    fold, and resumes after resolve + hysteresis."""
    m = bare_master
    wid = m._rpc_register_worker({"address": ""})["worker_id"]
    m._rpc_heartbeat({"worker_id": wid,
                      "firing": ["stage_backpressure"]})
    m._fold_worker_alerts()
    deadline = time.time() + 2.0
    while m._admission_paused is None and time.time() < deadline:
        time.sleep(0.01)
    assert m._admission_paused is not None
    # backpressure clears -> resolve arms the hysteresis hold; the
    # master's scan loop ticks the controller every 0.5 s
    m._rpc_heartbeat({"worker_id": wid, "firing": []})
    m._fold_worker_alerts()
    deadline = time.time() + 10.0
    while m._admission_paused is not None and time.time() < deadline:
        time.sleep(0.05)
    assert m._admission_paused is None


def test_preemption_notice_fences_assignment(bare_master):
    m = bare_master
    w0 = m._rpc_register_worker({"address": ""})["worker_id"]
    w1 = m._rpc_register_worker({"address": ""})["worker_id"]
    bulk = _BulkJob(bulk_id=0, spec_blob=b"", task_timeout=0.0)
    bulk.job_tasks[0] = {(0, t) for t in range(4)}
    for t in range(4):
        bulk.task_rows[(0, t)] = 1
    bulk.queue[0] = collections.deque(range(4))
    bulk.job_rr.append(0)
    bulk.total_tasks = 4
    with m._lock:
        m._bulk = bulk
        m._history[0] = bulk
    before = _counter("scanner_tpu_worker_preempt_notices_total")
    m._rpc_heartbeat({"worker_id": w0, "preempting": True})
    assert _counter(
        "scanner_tpu_worker_preempt_notices_total") == before + 1
    # the fenced worker gets nothing new; a healthy sibling does
    assert m._rpc_next_work(
        {"worker_id": w0, "bulk_id": 0})["status"] == "wait"
    assert m._rpc_next_work(
        {"worker_id": w1, "bulk_id": 0})["status"] == "task"
    # the notice is idempotent (one counter bump per worker)
    m._rpc_heartbeat({"worker_id": w0, "preempting": True})
    assert _counter(
        "scanner_tpu_worker_preempt_notices_total") == before + 1


def test_master_statusz_carries_remediation_panel(bare_master):
    st = bare_master._statusz()
    assert "remediation" in st
    names = {p["name"] for p in st["remediation"]["playbooks"]}
    assert {"admission_pause", "frame_cache_shrink",
            "ladder_rewarm", "autoscale_up"} <= names


# ---------------------------------------------------------------------------
# cluster e2e (in-process master + workers)
# ---------------------------------------------------------------------------

@pytest.fixture()
def cluster3(tmp_path):
    """Master + 3 in-process workers over a packed-int source table."""
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("ctl_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    master = Master(db_path=db_path, no_workers_timeout=30.0)
    addr = f"localhost:{master.port}"
    workers = [Worker(addr, db_path=db_path) for _ in range(3)]
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, workers, db_path
    faults.clear()
    sc.stop()
    for w in workers:
        w.stop()
    master.stop()


def _run_golden(sc, out_name: str, **perf_kw):
    col = sc.io.Input([NamedStream(sc, "ctl_src")])
    col = sc.ops.CtlSlowDouble(x=col)
    out = NamedStream(sc, out_name)
    sc.run(sc.io.Output(col, [out]), PerfParams.manual(2, 2, **perf_kw),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return [bytes(r) for r in out.load()]


def test_preempt_30pct_mid_bulk_bit_exact_no_strikes(cluster3):
    """The headline chaos plan (ISSUE/ROADMAP item 5): preempt ~30% of
    workers (1 of 3) mid-bulk under load.  Output bit-exact vs a clean
    run, requeues strike-free, the master fenced the victim, and no
    `unhealthy` roll-up page stands once the rule hold-downs pass."""
    sc, master, workers, _dbp = cluster3
    victim = workers[1]
    strikes0 = _counter("scanner_tpu_blacklist_strikes_total")
    notices0 = _counter("scanner_tpu_worker_preempt_notices_total")
    # 2nd heartbeat after arming ≈ 1–2 s in: mid-bulk for this load
    # (48 rows x 0.25 s / 3 workers ≈ 4 s)
    faults.install(f"worker.preempt:raise:"
                   f"match={victim.worker_id}:n=2:times=1")
    got = _run_golden(sc, "ctl_faulted")
    assert faults.fired("worker.preempt") == 1, \
        "preemption never fired (bulk too fast?)"
    faults.clear()
    golden = _run_golden(sc, "ctl_clean")
    assert got == golden == EXPECT
    # strike-free: a preemption is routine, not a task failure
    assert _counter("scanner_tpu_blacklist_strikes_total") == strikes0
    # the master saw the notice (fence) and the worker drained out
    assert _counter(
        "scanner_tpu_worker_preempt_notices_total") == notices0 + 1
    assert victim.preempting() and victim.draining()
    assert _counter("scanner_tpu_worker_preemptions_total") >= 1
    # the cluster re-absorbed the work on the two survivors
    st = master._rpc_job_status({})
    assert st["num_workers"] == 2
    # no standing page after hold-down: give the health engine a few
    # ticks past every default rule's for_seconds, then require the
    # master roll-up not unhealthy and no heartbeat-stale alert for
    # the departed worker (its gauge child was dropped at drain)
    deadline = time.time() + 8.0
    while time.time() < deadline:
        h = _health.status_dict()
        stale = [f for f in h.get("firing", ())
                 if f.get("rule") == "worker_heartbeat_stale"]
        if h.get("status") != "unhealthy" and not stale:
            break
        time.sleep(0.25)
    h = _health.status_dict()
    assert h.get("status") != "unhealthy", h
    assert not [f for f in h.get("firing", ())
                if f.get("rule") == "worker_heartbeat_stale"], h


def test_scale_down_drain_never_kills_in_flight(cluster3):
    """The autoscaler's scale-down contract end to end: reducing
    capacity through the drain path mid-bulk loses no work — the
    drained worker finishes what it holds, the rest requeues
    strike-free, output stays bit-exact."""
    sc, master, workers, _dbp = cluster3
    strikes0 = _counter("scanner_tpu_blacklist_strikes_total")
    drains0 = _counter("scanner_tpu_worker_drains_total")
    result = {}

    def run():
        result["rows"] = _run_golden(sc, "ctl_scaledown")

    t = threading.Thread(target=run)
    t.start()
    time.sleep(1.5)               # mid-bulk
    # what deploy.Cluster.scale does to the surplus pod: SIGTERM ->
    # drain (finish in-flight, deregister) — never a kill
    workers[2].drain()
    t.join(timeout=120)
    assert not t.is_alive()
    assert result["rows"] == EXPECT
    assert _counter("scanner_tpu_blacklist_strikes_total") == strikes0
    assert _counter("scanner_tpu_worker_drains_total") == drains0 + 1
    assert master._rpc_job_status({})["num_workers"] == 2


def test_named_plan_worker_preempt_registered():
    assert "worker-preempt" in faults.NAMED_PLANS
    rules = faults.parse_plan(faults.NAMED_PLANS["worker-preempt"])
    assert rules[0].site == "worker.preempt"
