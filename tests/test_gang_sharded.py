"""Sharded gang execution (docs/robustness.md §Sharded gangs;
scanner_tpu/engine/gang.py sharded body + engine/service.py shard fold).

Layers:
  * pure units — ceil-chunk layout properties shared by the digest and
    data planes, uneven host_local_array validation, deterministic
    null-row digests, frame-cache host-shard page scoping, the
    sharded/halo config gates;
  * in-process master units — role replies carry the master-decided
    sharded/halo flags (PerfParams AND the master gate AND gang size),
    and the shard commit fold classifies ok / mismatch / partial from
    the writer's FinishedWork against early member acks;
  * spawned e2e (slow) — bit-exact equivalence sweeps
    sharded vs replicated vs single-host over real virtual multi-host
    gangs (stateless uneven rows, stencil-with-halo over synthesized
    video, null-interleaved, Gather sampling), per-member decode
    isolation (~1/N rows each), a SIGKILL-mid-collective chaos run that
    re-forms smaller and stays bit-exact with zero strikes, and the
    2-process uneven all_gather_rows proof over a real gloo runtime.
"""

import os
import struct
import subprocess
import sys
import time
from typing import Sequence

import cloudpickle
import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, FrameType, Kernel,
                         NamedStream, NamedVideoStream, NullElement,
                         PerfParams, register_op)
from scanner_tpu.common import ScannerException
from scanner_tpu.engine import framecache as fc
from scanner_tpu.engine import gang as egang
from scanner_tpu.engine.service import MASTER_SERVICE, Master, Worker
from scanner_tpu.parallel import distributed as dist
from scanner_tpu.util import faults
from scanner_tpu.util import metrics as _mx

cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.chaos

N_ROWS = 10


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="ShardDouble")
class ShardDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return _pk(2 * struct.unpack("<q", x)[0])


@register_op(name="ShardStencilSum", stencil=[-1, 0])
class ShardStencilSum(Kernel):
    def execute(self, frame: Sequence[FrameType]) -> bytes:
        return _pk(int(np.asarray(frame, np.int64).sum()))


@register_op(name="ShardFrameSum")
class ShardFrameSum(Kernel):
    def execute(self, frame: FrameType) -> bytes:
        return _pk(int(np.asarray(frame, np.int64).sum()))


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    if labels:
        for s in entry.get("samples", []):
            if all(s["labels"].get(k) == v for k, v in labels.items()):
                return s["value"]
        return 0.0
    return sum(s["value"] for s in entry.get("samples", []))


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    fc.set_host_shard(None)
    yield
    faults.clear()
    fc.set_host_shard(None)


# ---------------------------------------------------------------------------
# pure units
# ---------------------------------------------------------------------------

def test_ceil_chunk_layout_properties():
    """The one row layout both planes share: equal ceil(n/num) chunks,
    remainder on the last non-empty shard, tail shards empty — and
    shard_range (the gang data plane) is exactly shard_rows."""
    for n in (0, 1, 5, 8, 10, 17, 64):
        for num in (1, 2, 3, 4, 7, 9):
            chunk = dist.ceil_chunk(n, num)
            assert chunk * num >= n
            spans = [dist.shard_rows(n, p, num) for p in range(num)]
            assert spans == [egang.shard_range(n, p, num)
                             for p in range(num)]
            assert spans[0][0] == 0 and spans[-1][1] == n
            for (alo, ahi), (blo, bhi) in zip(spans, spans[1:]):
                assert ahi == blo and alo <= ahi
            lens = [hi - lo for lo, hi in spans]
            # every shard is a full chunk until the remainder, then
            # one short shard, then only empties
            short = [i for i, ln in enumerate(lens) if 0 < ln < chunk]
            assert len(short) <= 1
            if short:
                assert all(ln == 0 for ln in lens[short[0] + 1:])
    with pytest.raises(ScannerException):
        dist.ceil_chunk(4, 0)


def test_host_local_array_uneven_validation():
    """The uneven staging path's contracts that don't need a second
    process: a named leading axis is required, and a host block larger
    than the ceil-chunk is rejected."""
    from jax.sharding import PartitionSpec

    from scanner_tpu.parallel.mesh import host_mesh

    mesh = host_mesh(1)
    block = np.arange(12, dtype=np.float32).reshape(3, 4)
    with pytest.raises(ScannerException, match="named mesh axis"):
        dist.host_local_array(mesh, PartitionSpec(None), block,
                              global_rows=3)
    with pytest.raises(ScannerException, match="exceeds"):
        dist.host_local_array(mesh, ("hosts",), np.zeros((5, 2)),
                              global_rows=3)
    # single-host roundtrip: uneven staging degenerates to identity
    out = dist.all_gather_rows(mesh, "hosts", block, global_rows=3)
    assert np.array_equal(out, block)


def test_digest_rows_null_and_object_deterministic():
    """Null rows digest as a fixed sentinel and object rows by count —
    NEVER by buffer pointer bytes, which would differ across gang
    member processes and break the cross-host agreement."""
    rows = [b"abc", NullElement(), np.arange(3)]
    assert egang._digest_rows(rows) == egang._digest_rows(
        [b"abc", NullElement(), np.arange(3)])
    # a null is distinguishable from an absent row and from data
    assert egang._digest_rows([NullElement()]) \
        != egang._digest_rows([])
    assert egang._digest_rows([NullElement()]) \
        != egang._digest_rows([b""])
    # object-dtype arrays contribute a constant (their buffer is
    # process-local pointers), so two distinct instances agree
    o1 = np.array([object(), object()], dtype=object)
    o2 = np.array([object(), object()], dtype=object)
    assert egang._digest_rows([o1]) == egang._digest_rows([o2])


def test_framecache_pages_scoped_by_host_shard():
    """Pages staged under one shard identity never serve another (or
    the unsharded identity): a re-formed gang at a different N — whose
    shard boundaries moved — can never gather a stale page."""
    import jax.numpy as jnp

    pool = fc.FrameCache()
    old_pf = fc._page_frames_cfg
    fc.set_page_frames(4)
    try:
        rows = np.arange(4)
        block = jnp.asarray(np.arange(4 * 3, dtype=np.uint8)
                            .reshape(4, 3))
        fc.set_host_shard("s0of2")
        p = pool.plan(None, ("db", 1), "frame", 0, "rgb", rows, 4)
        assert p.skey[0] == "s0of2"
        assert not p.hit_mask.any()
        pool._offer_block(p, rows, block, (1, 3))
        p.lease.release()
        warm = pool.plan(None, ("db", 1), "frame", 0, "rgb", rows, 4)
        assert warm.hit_mask.all(), "page never completed"
        warm.lease.release()
        # the same rows under a DIFFERENT shard identity: all misses
        fc.set_host_shard("s1of2")
        other = pool.plan(None, ("db", 1), "frame", 0, "rgb", rows, 4)
        assert not other.hit_mask.any()
        other.lease.release()
        # ... and under the unsharded identity too
        fc.set_host_shard(None)
        plain = pool.plan(None, ("db", 1), "frame", 0, "rgb", rows, 4)
        assert plain.skey == (("db", 1), "frame", 0, "rgb")
        assert not plain.hit_mask.any()
        plain.lease.release()
    finally:
        fc.set_page_frames(old_pf)


def test_sharded_config_gates_roundtrip():
    assert "sharded" in egang.CONFIG_KEYS
    assert "halo_exchange" in egang.CONFIG_KEYS
    old_s, old_h = egang.sharded_enabled(), egang.halo_enabled()
    try:
        egang.set_sharded(False)
        assert not egang.sharded_enabled()
        egang.set_sharded(True)
        assert egang.sharded_enabled()
        egang.set_halo(False)
        assert not egang.halo_enabled()
    finally:
        egang.set_sharded(old_s)
        egang.set_halo(old_h)


# ---------------------------------------------------------------------------
# in-process master units
# ---------------------------------------------------------------------------

def _seed_db(tmp_path, name="db"):
    db_path = str(tmp_path / name)
    sc = Client(db_path=db_path)
    sc.new_table("shard_src", ["output"],
                 [[_pk(100 + i)] for i in range(N_ROWS)])
    return sc, db_path


def _spec_blob(sc, out_name, gang_hosts=2, io=4, **perf_kw):
    col = sc.io.Input([NamedStream(sc, "shard_src")])
    col = sc.ops.ShardDouble(x=col)
    out = NamedStream(sc, out_name)
    node = sc.io.Output(col, [out])
    return cloudpickle.dumps({
        "outputs": [node],
        "perf": PerfParams.manual(2, io, gang_hosts=gang_hosts,
                                  **perf_kw),
        "cache_mode": CacheMode.Overwrite.value})


def _register(master, n, base_port=7200):
    return [master._rpc_register_worker(
        {"address": "", "gang_address": f"localhost:{base_port + i}"}
    )["worker_id"] for i in range(n)]


def _form(master, bid, wids):
    roles = {}
    deadline = time.time() + 10
    while time.time() < deadline and len(roles) < len(wids):
        for wid in wids:
            r = master._rpc_next_work({"worker_id": wid,
                                       "bulk_id": bid})
            if r.get("status") == "gang":
                roles[wid] = r
        if not roles:
            time.sleep(0.02)
    assert roles, "no gang formed"
    return roles


def test_role_reply_carries_master_decided_mode(tmp_path):
    """The sharded/halo decision is minted ONCE, by the master, and
    rides the role reply — members can never disagree about the
    evaluation mode.  PerfParams.gang_sharded=False, the master-side
    gate, and a singleton gang each force it off."""
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    old = egang.sharded_enabled()
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "mode_on"),
                              "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        for r in roles.values():
            assert r["sharded"] is True and r["halo"] is True
        m.stop()
        # PerfParams opt-out (fresh db: an unfinished bulk would be
        # recovered by a successor master over the same journal)
        sc2, db2 = _seed_db(tmp_path, "db2")
        m = Master(db_path=db2, no_workers_timeout=60.0)
        try:
            w0, w1 = _register(m, 2)
            bid2 = m._rpc_new_job({"spec": _spec_blob(
                sc2, "mode_perf", gang_sharded=False),
                "token": "t2"})["bulk_id"]
            roles = _form(m, bid2, [w0, w1])
            assert all(r["sharded"] is False
                       for r in roles.values())
        finally:
            sc2.stop()
    finally:
        egang.set_sharded(old)
        m.stop()
        sc.stop()


def test_role_reply_master_gate_and_singleton(tmp_path):
    sc, db_path = _seed_db(tmp_path)
    old_s = egang.sharded_enabled()
    old_t = egang.form_timeout_s()
    egang.set_sharded(False)  # master-side gate wins over PerfParams
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "mode_gate"),
                              "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        assert all(r["sharded"] is False for r in roles.values())
        m.stop()
        # a singleton gang has nothing to shard: flag is off even with
        # every gate open (fresh db: see the opt-out test above)
        egang.set_sharded(True)
        egang.set_form_timeout_s(0.05)
        sc2, db2 = _seed_db(tmp_path, "db2")
        m2 = Master(db_path=db2, no_workers_timeout=60.0)
        try:
            (v0,) = _register(m2, 1)
            bid = m2._rpc_new_job({"spec": _spec_blob(
                sc2, "mode_one", gang_hosts=1),
                "token": "t"})["bulk_id"]
            roles = _form(m2, bid, [v0])
            assert all(r["sharded"] is False for r in roles.values())
        finally:
            m2.stop()
            sc2.stop()
    finally:
        egang.set_sharded(old_s)
        egang.set_form_timeout_s(old_t)
        sc.stop()


def test_shard_commit_fold_ok_mismatch_partial(tmp_path):
    """The master-side shard commit fold over the real RPC path: the
    writer's FinishedWork digests vs early GangMemberDone acks —
    ok when shards sum to the collective total and acked ranks agree,
    mismatch when either check fails, partial when digests are
    missing.  Never a strike: the fold is observational."""
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        # io=2 over 10 rows -> 5 tasks -> 5 gangs: one per scenario
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "fold", io=2),
                              "token": "t"})["bulk_id"]
        strikes0 = _counter("scanner_tpu_blacklist_strikes_total")

        def run_gang(shard_digest_ack, digest, shard_digests):
            roles = _form(m, bid, [w0, w1])
            r = next(iter(roles.values()))
            m0 = w0 if roles[w0]["process_id"] == 0 else w1
            m1 = w1 if m0 == w0 else w0
            base = dict(bulk_id=bid, gang_id=r["gang_id"],
                        epoch=r["epoch"], job_idx=r["job_idx"],
                        task_idx=r["task_idx"], attempt=r["attempt"])
            if shard_digest_ack is not None:
                assert m._rpc_gang_member_done(
                    dict(base, worker_id=m1,
                         shard_digest=shard_digest_ack))["ok"]
            assert m._rpc_finished_work(
                dict(base, worker_id=m0, digest=digest,
                     shard_digests=shard_digests)) == {"ok": True}

        def fold(result):
            return _counter(
                "scanner_tpu_gang_shard_commit_folds_total",
                result=result)

        ok0, mis0, par0 = fold("ok"), fold("mismatch"), fold("partial")
        run_gang(7, (5 + 7) & 0xFFFFFFFF, [5, 7])           # ok
        assert fold("ok") == ok0 + 1
        run_gang(None, (5 + 7) & 0xFFFFFFFF, [5, 8])        # bad sum
        assert fold("mismatch") == mis0 + 1
        run_gang(9, (5 + 7) & 0xFFFFFFFF, [5, 7])           # ack differs
        assert fold("mismatch") == mis0 + 2
        run_gang(None, (5 + 7) & 0xFFFFFFFF, [12])          # short list
        assert fold("partial") == par0 + 1
        run_gang(None, None, [5, 7])                        # no total
        assert fold("partial") == par0 + 2
        # observational only: no strikes for any fold outcome
        assert _counter("scanner_tpu_blacklist_strikes_total") \
            == strikes0
    finally:
        m.stop()
        sc.stop()


# ---------------------------------------------------------------------------
# spawned e2e (slow): bit-exact equivalence + chaos
# ---------------------------------------------------------------------------

def _assert_rows_equal(a, b, ctx=""):
    assert len(a) == len(b), f"{ctx}: {len(a)} vs {len(b)} rows"
    for i, (x, y) in enumerate(zip(a, b)):
        if isinstance(x, NullElement) or isinstance(y, NullElement):
            assert isinstance(x, NullElement) \
                and isinstance(y, NullElement), f"{ctx} row {i}"
        elif isinstance(x, (bytes, bytearray)) \
                or isinstance(y, (bytes, bytearray)):
            assert bytes(x) == bytes(y), f"{ctx} row {i}"
        else:
            assert np.array_equal(np.asarray(x), np.asarray(y)), \
                f"{ctx} row {i}"


def _run_one(client, build, out_name, perf):
    out = NamedStream(client, out_name)
    client.run(client.io.Output(build(client), [out]), perf,
               cache_mode=CacheMode.Overwrite, show_progress=False)
    return list(out.load())


def _equivalence(tmp_path, build, wp=1, io=5, seed_table=False,
                 video_frames=0):
    """Run `build` single-host, then replicated and sharded on a real
    2-worker gang over the same db; return the three row lists (already
    asserted bit-exact) plus the shard-metric deltas of the sharded
    run."""
    from scanner_tpu import video as scv

    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    if seed_table:
        seed.new_table("shard_src", ["output"],
                       [[_pk(100 + i)] for i in range(N_ROWS)])
    if video_frames:
        vid = str(tmp_path / "v.mp4")
        scv.synthesize_video(vid, num_frames=video_frames, width=64,
                             height=48, fps=24, keyint=8)
        seed.ingest_videos([("shard_vid", vid)])
    single = _run_one(seed, build, "eq_single",
                      PerfParams.manual(wp, io))

    m = Master(db_path=db_path, no_workers_timeout=60.0)
    addr = f"localhost:{m.port}"
    old_t = egang.form_timeout_s()
    egang.set_form_timeout_s(6.0)
    workers = [Worker(addr, db_path=db_path) for _ in range(2)]
    sc = Client(db_path=db_path, master=addr)
    try:
        repl = _run_one(sc, build, "eq_repl",
                        PerfParams.manual(wp, io, gang_hosts=2,
                                          gang_sharded=False))
        s0 = {k: _counter(k) for k in egang.GANG_SHARD_SERIES[:3]}
        d0 = {r: _counter("scanner_tpu_gang_shard_decode_rows_total",
                          role=r) for r in ("coordinator", "member")}
        shard = _run_one(sc, build, "eq_shard",
                         PerfParams.manual(wp, io, gang_hosts=2))
        deltas = {k: _counter(k) - s0[k]
                  for k in egang.GANG_SHARD_SERIES[:3]}
        decode = {
            r: _counter("scanner_tpu_gang_shard_decode_rows_total",
                        role=r) - d0.get(r, 0.0)
            for r in ("coordinator", "member")}
    finally:
        sc.stop()
        for w in workers:
            w.stop()
        m.stop()
        egang.set_form_timeout_s(old_t)
        seed.stop()
    _assert_rows_equal(single, repl, "single-vs-replicated")
    _assert_rows_equal(single, shard, "single-vs-sharded")
    assert _counter("scanner_tpu_blacklist_strikes_total") == 0
    return single, deltas, decode


@pytest.mark.slow
def test_equivalence_stateless_uneven(tmp_path):
    """Stateless kernel over an UNEVEN split (io=5 over 2 members ->
    3+2 rows): sharded == replicated == single-host bit-exact, and
    each member evaluates only its shard of every task."""
    def build(s):
        return s.ops.ShardDouble(
            x=s.io.Input([NamedStream(s, "shard_src")]))

    rows, deltas, decode = _equivalence(tmp_path, build,
                                        seed_table=True)
    assert [bytes(r) for r in rows] \
        == [_pk(2 * (100 + i)) for i in range(N_ROWS)]
    assert deltas["scanner_tpu_gang_shard_rows_total"] == N_ROWS
    # per-member decode isolation under the ceil-chunk split of the
    # 2 tasks (5 rows each -> 3+2): the coordinator plans 6 rows, the
    # other member 4 — each ~1/2, never the full 10
    assert decode["coordinator"] == 6 and decode["member"] == 4


@pytest.mark.slow
def test_equivalence_stencil_halo(tmp_path):
    """Stencil windows that straddle the shard boundary ride the halo
    exchange (halo bytes flow) instead of widening each member's
    decode — and the output is still bit-exact everywhere."""
    def build(s):
        return s.ops.ShardStencilSum(
            frame=s.io.Input([NamedVideoStream(s, "shard_vid")]))

    rows, deltas, _ = _equivalence(tmp_path, build, io=8,
                                   video_frames=16)
    assert len(rows) == 16
    assert deltas["scanner_tpu_gang_shard_rows_total"] == 16
    assert deltas["scanner_tpu_gang_shard_halo_bytes_total"] > 0
    # each member decodes ~1/2 the rows: the only extra decode is the
    # stencil back-reach past a TASK edge, never the shard boundary
    assert deltas["scanner_tpu_gang_shard_decode_rows_total"] <= 16 + 2


@pytest.mark.slow
def test_equivalence_null_interleaved(tmp_path):
    """RepeatNull-spaced domains: null rows cross the member gather and
    the digest collective deterministically."""
    def build(s):
        f = s.io.Input([NamedVideoStream(s, "shard_vid")])
        ranged = s.streams.Range(f, [(0, 8)])
        spaced = s.streams.RepeatNull(ranged, [2])
        return s.ops.ShardFrameSum(frame=spaced)

    rows, deltas, _ = _equivalence(tmp_path, build, io=8,
                                   video_frames=16)
    assert len(rows) == 16
    assert any(isinstance(r, NullElement) for r in rows)
    assert any(not isinstance(r, NullElement) for r in rows)
    assert deltas["scanner_tpu_gang_shard_rows_total"] == 16


@pytest.mark.slow
def test_equivalence_gather_sampling(tmp_path):
    """Gather-sampled domains shard by OUTPUT row: members decode only
    the source frames their sampled rows reference."""
    def build(s):
        f = s.io.Input([NamedVideoStream(s, "shard_vid")])
        sampled = s.streams.Gather(f, [[0, 3, 9, 13]])
        return s.ops.ShardFrameSum(frame=sampled)

    rows, deltas, _ = _equivalence(tmp_path, build, io=4,
                                   video_frames=16)
    assert len(rows) == 4
    assert deltas["scanner_tpu_gang_shard_rows_total"] == 4


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_sharded_chaos_sigkill_reforms_smaller_bit_exact(tmp_path):
    """SIGKILL one member the moment it enters the cross-host
    collective of a SHARDED gang: the gang aborts strike-free, re-forms
    SMALLER (the survivor recomputes shard_range over num=1 and runs
    the whole row range), and the output is bit-exact."""
    from scanner_tpu.engine.rpc import wait_for_server
    from scanner_tpu.util.jaxenv import cpu_only_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("shard_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    env = cpu_only_env()
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SCANNER_TPU_FAULTS", None)
    env["SCANNER_TPU_GANG_INIT_TIMEOUT"] = "30"
    env["SCANNER_TPU_GANG_FORM_TIMEOUT"] = "6"
    port = _free_port()
    addr = f"localhost:{port}"

    def spawn(script, argv, plan=None):
        e = dict(env)
        if plan:
            e["SCANNER_TPU_FAULTS"] = plan
        return subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", script),
             *argv], env=e)

    procs = [spawn("spawn_master.py", [db_path, str(port)])]
    procs.append(spawn("spawn_worker.py", [addr, db_path],
                       plan=faults.NAMED_PLANS["gang-host-loss"]))
    procs.append(spawn("spawn_worker.py", [addr, db_path]))
    sc = None
    try:
        wait_for_server(addr, MASTER_SERVICE, timeout=60.0)
        sc = Client(db_path=db_path, master=addr)
        deadline = time.time() + 60
        while time.time() < deadline \
                and sc.job_status().get("num_workers", 0) < 2:
            time.sleep(0.25)
        col = sc.io.Input([NamedStream(sc, "shard_src")])
        col = sc.ops.ShardDouble(x=col)
        out = NamedStream(sc, "chaos_out")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.manual(5, N_ROWS // 2, gang_hosts=2),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        rows = [bytes(r) for r in out.load()]
        assert rows == [_pk(2 * (100 + i)) for i in range(N_ROWS)]
        time.sleep(0.5)
        crashed = [p for p in procs
                   if p.poll() == faults.CRASH_EXIT_CODE]
        assert crashed, "gang.collective crash never fired"
        snap = sc.metrics()

        def tot(name):
            return sum(s.get("value", 0) for s in
                       snap.get(name, {}).get("samples", []))

        assert tot("scanner_tpu_gang_aborted_total") >= 1
        assert tot("scanner_tpu_gang_reforms_total") >= 1
        assert tot("scanner_tpu_blacklist_strikes_total") == 0
        # the fold ran for every sharded commit, and never flagged
        folds = snap.get("scanner_tpu_gang_shard_commit_folds_total",
                         {}).get("samples", [])
        assert all(s["labels"].get("result") == "ok" for s in folds)
    finally:
        if sc is not None:
            sc.stop()
        seed.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


@pytest.mark.slow
def test_multihost_uneven_all_gather_rows():
    """The uneven staging path over a REAL 2-process gloo runtime:
    7 rows over 2 host shards (4 + 3, zero-padded to even staging)
    gather back to the exact logical rows on every rank."""
    from multihost_child import free_port, spawn_multihost

    outs = spawn_multihost(n_processes=2, devices_per_process=2,
                           timeout=240, port=free_port(),
                           mode="gather")
    assert len(outs) == 2
    lines = [ln for o in outs for ln in o.splitlines()
             if ln.startswith("MULTIHOST_GATHER")]
    assert len(lines) == 2 and len(set(lines)) == 1, lines
    assert lines[0].endswith("ok"), lines
