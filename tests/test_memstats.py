"""Memory observability (util/memstats.py + its engine wiring).

Covers the allocation ledger (register/finalizer release, peaks, task/
trace attribution from the tracing context), the `memory.pressure`
fault site driving the full OOM-forensics + transient-requeue path on
an in-process CPU cluster (bit-exact output, report naming the top
ledger entry with its owning task and trace id), the /statusz Memory
panel, scanner_top --json, the leak-guard fixture, historical-bulk
retention/compaction, and the JSON structured-log format.
"""

import gc
import json
import logging
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
from scanner_tpu.common import DeviceOutOfMemory
from scanner_tpu.engine.batch import ColumnBatch
from scanner_tpu.util import faults
from scanner_tpu.util import memstats
from scanner_tpu.util import metrics as _mx
from scanner_tpu.util import tracing as _tr

N_FRAMES = 24


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    return sum(s["value"] for s in entry.get("samples", [])
               if all(s["labels"].get(k) == v for k, v in labels.items()))


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# ledger units
# ---------------------------------------------------------------------------

def test_ledger_register_release_and_peaks():
    base_live = memstats.live_bytes(device="unit:0")
    assert base_live == 0
    e1 = memstats.register(1000, "unit:0", "staging", task="0,1",
                           trace_id="t1")
    e2 = memstats.register(500, "unit:0", "sink")
    assert memstats.live_bytes(device="unit:0") == 1500
    assert memstats.live_bytes(device="unit:0", kind="staging") == 1000
    assert memstats.watermark_bytes(device="unit:0") == 1500
    top = [e for e in memstats.top_entries(5)
           if e["device"] == "unit:0"]
    assert top[0]["bytes"] == 1000 and top[0]["task"] == "0,1" \
        and top[0]["trace_id"] == "t1"
    memstats.release(e1)
    memstats.release(e1)  # double release is idempotent
    memstats.release(e2)
    assert memstats.live_bytes(device="unit:0") == 0
    # the watermark survives release: peak HBM is the point
    assert memstats.watermark_bytes(device="unit:0") == 1500
    summary = {(s["device"], s["kind"]): s
               for s in memstats.ledger_summary()}
    assert summary[("unit:0", "staging")]["peak_bytes"] == 1000
    assert summary[("unit:0", "staging")]["live_bytes"] == 0


def test_track_array_releases_on_collection():
    a = np.zeros((10, 10), np.float32)
    eid = memstats.track_array(a, "staging", device="unit:gc")
    assert eid is not None
    assert memstats.live_bytes(device="unit:gc") == 400
    del a
    gc.collect()
    assert memstats.live_bytes(device="unit:gc") == 0
    assert memstats.watermark_bytes(device="unit:gc") == 400
    # a raw /metrics scrape alone balances the counters: the live-gauge
    # sampler flushes the finalizer-deferred release counts, so
    # allocs - releases = live entries holds on an otherwise-idle
    # process (the documented leak diagnostic)
    snap = _mx.registry().snapshot()

    def val(name):
        return sum(s["value"] for s in snap.get(name, {})["samples"]
                   if s["labels"].get("device") == "unit:gc")

    assert val("scanner_tpu_ledger_allocs_total") == 1
    assert val("scanner_tpu_ledger_releases_total") == 1
    assert val("scanner_tpu_ledger_live_bytes") == 0


def test_to_device_registers_staging_with_owner():
    """The staging hot path: to_device registers the batch against the
    active task span's (job, task) and trace id, and the entry releases
    when the staged batch is collected."""
    tracer = _tr.default_tracer()
    with _tr.start_span(tracer, "task", job=4, task=7) as span:
        staged = ColumnBatch(
            np.arange(4), np.zeros((4, 8, 8, 3), np.uint8)).to_device()
        mine = [e for e in memstats.entries()
                if e["trace_id"] == span.trace_id]
        assert len(mine) == 1
        assert mine[0]["kind"] == "staging"
        assert mine[0]["bytes"] == 4 * 8 * 8 * 3
        assert mine[0]["task"] == "4,7"
        trace_id = span.trace_id
    del staged
    gc.collect()
    assert not [e for e in memstats.entries()
                if e["trace_id"] == trace_id]


def test_device_stats_gracefully_absent_on_cpu():
    # the CPU backend reports no memory_stats: the HBM view is empty,
    # never an error — and the status dict still renders
    assert memstats.device_memory_stats() == {}
    st = memstats.status_dict()
    assert st["enabled"] is True
    assert isinstance(st["ledger"], list)


def test_is_oom_classification():
    assert memstats.is_oom(DeviceOutOfMemory("x"))
    xla_like = type("XlaRuntimeError", (Exception,), {})
    assert memstats.is_oom(
        xla_like("RESOURCE_EXHAUSTED: Out of memory allocating 1GB"))
    assert not memstats.is_oom(xla_like("INVALID_ARGUMENT: shape"))
    assert not memstats.is_oom(ValueError("RESOURCE_EXHAUSTED"))
    from scanner_tpu.engine.service import _is_transient_failure
    assert _is_transient_failure(DeviceOutOfMemory("injected"))


def test_note_oom_builds_one_shot_report():
    pinned = np.zeros((100,), np.uint8)
    memstats.track_array(pinned, "staging", device="unit:oom")
    before = _counter("scanner_tpu_device_oom_events_total",
                      site="unit-test")
    report = memstats.note_oom(DeviceOutOfMemory("RESOURCE_EXHAUSTED"),
                               site="unit-test", detail="d")
    assert _counter("scanner_tpu_device_oom_events_total",
                    site="unit-test") == before + 1
    assert report["site"] == "unit-test"
    assert "DeviceOutOfMemory" in report["reason"]
    assert any(e["device"] == "unit:oom" for e in report["top_entries"])
    last = memstats.last_report()
    assert last is not None and last["seq"] == report["seq"]
    assert report["node"]  # stamped at the source, not by the shipper
    # the global claim-once cursor hands each report out exactly once
    got = memstats.take_unshipped_report()
    assert got is not None and got["seq"] == report["seq"]
    assert memstats.take_unshipped_report() is None
    del pinned
    gc.collect()


def test_memory_report_local_mode(tmp_path):
    sc = Client(db_path=str(tmp_path / "db"))
    try:
        rep = sc.memory_report()
        assert "memory" in rep and "reports" in rep
        assert isinstance(rep["memory"]["ledger"], list)
    finally:
        sc.stop()


# ---------------------------------------------------------------------------
# the full OOM-forensics path on an in-process cluster
# ---------------------------------------------------------------------------

@pytest.fixture()
def mem_cluster(tmp_path, monkeypatch):
    """Master (with /metrics+/statusz) + 1 worker + client over an
    ingested video, with device staging forced on the virtual
    multi-device CPU host so the ledger paths actually run."""
    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    from scanner_tpu import video as scv
    from scanner_tpu.engine.service import Master, Worker

    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=12)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("mvid", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0,
                    metrics_port=0)
    addr = f"localhost:{master.port}"
    worker = Worker(addr, db_path=db_path, pipeline_instances=2)
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, worker, addr
    faults.clear()
    sc.stop()
    worker.stop()
    master.stop()


def _run_histogram(sc, out_name: str):
    import scanner_tpu.kernels  # noqa: F401  (registers Histogram)
    frame = sc.io.Input([NamedVideoStream(sc, "mvid")])
    h = sc.ops.Histogram(frame=frame)
    out = NamedStream(sc, out_name)
    job_id = sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
                    cache_mode=CacheMode.Overwrite, show_progress=False)
    return job_id, list(out.load())


@pytest.mark.chaos
def test_memory_pressure_requeues_bit_exact_with_report(mem_cluster):
    """The acceptance path: induced memory pressure (memory.pressure on
    CPU) -> one-shot memory report naming the top ledger entry with its
    task and trace id -> strike-free transient requeue -> bit-exact
    completion; /statusz carries the Memory panel and the post-bulk
    straggler/trace queries still answer."""
    sc, master, worker, addr = mem_cluster

    # clean reference run (faults disarmed)
    _job0, expect = _run_histogram(sc, "mem_clean")
    assert expect

    # a pinned co-scheduled buffer: the deterministic "who holds the
    # HBM" answer the OOM report must name (bigger than any task batch)
    tracer = _tr.default_tracer()
    with _tr.start_span(tracer, "task", job=99, task=0) as pin_span:
        pinned = ColumnBatch(
            np.arange(64),
            np.zeros((64, 64, 48, 3), np.uint8)).to_device()
        pin_trace = pin_span.trace_id

    transient_before = _counter("scanner_tpu_transient_retries_total")
    oom_before = _counter("scanner_tpu_device_oom_events_total",
                          site="staging")
    faults.install(faults.NAMED_PLANS["memory-pressure"])
    job_id, got = _run_histogram(sc, "mem_faulted")
    fired = faults.fired("memory.pressure")
    faults.clear()

    # the fault FIRED exactly once, and the output is bit-exact anyway
    assert fired == 1
    assert _counter("scanner_tpu_faults_injected_total",
                    site="memory.pressure", mode="raise") >= 1
    assert len(got) == len(expect)
    assert all(np.array_equal(a, b) for a, b in zip(got, expect))
    # strike-free transient requeue (PR 3 machinery), not a blacklist
    assert _counter("scanner_tpu_transient_retries_total") \
        >= transient_before + 1
    assert _counter("scanner_tpu_device_oom_events_total",
                    site="staging") == oom_before + 1

    # the memory report reached the master and names the pinned entry
    # with its owning task and trace id
    rep = sc.memory_report()
    assert rep["reports"], rep
    # reports accumulate newest-last (earlier tests may have left one)
    r = next(r for r in reversed(rep["reports"])
             if r.get("site") == "staging")
    assert "DeviceOutOfMemory" in r["reason"]
    top = r["top_entries"][0]
    assert top["task"] == "99,0"
    assert top["trace_id"] == pin_trace
    assert top["bytes"] == 64 * 64 * 48 * 3
    assert r["recent_spans"], "flight-recorder tail missing"

    # /statusz Memory panel (master role)
    port = master.metrics_server.port
    st = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=10).read())
    assert st["memory"]["oom_events"] >= 1
    assert isinstance(st["memory"]["ledger"], list)
    assert st["memory"]["last_oom"]["site"] == "staging"
    assert st["memory"]["worker_reports"] >= 1

    # ledger + HBM series exist on /metrics (device-labeled ledger
    # samples from the staged columns; HBM absent on CPU by design)
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    assert "scanner_tpu_ledger_allocs_total" in text
    assert 'kind="staging"' in text

    # retention: the finished bulk still answers straggler/trace pulls
    stragglers = sc.stragglers(job_id)
    assert stragglers["per_stage"].get("task", {}).get("count", 0) > 0
    trace = sc._cluster.get_trace(sc._cluster.last_bulk_id)
    assert trace["spans"], "span store vanished at bulk completion"

    del pinned
    gc.collect()


@pytest.mark.chaos
def test_scanner_top_json_smoke(mem_cluster):
    """scanner_top --json against a live master: exit 0, parseable
    JSON mirroring --once (status + per-node counters + per-device
    utilization/memory maps) — scripts stop scraping the human table."""
    sc, _master, _worker, addr = mem_cluster
    _run_histogram(sc, "top_json_out")

    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + \
        env.get("PYTHONPATH", "")
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "scanner_top.py")
    r = subprocess.run(
        [sys.executable, tool, "--master", addr, "--json"],
        env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["master"] == addr
    assert doc["status"]["tasks_done"] == doc["status"]["total_tasks"]
    workers = [n for n in doc["nodes"] if n.startswith("worker")]
    assert workers, doc["nodes"]
    wn = doc["nodes"][workers[0]]
    for key in ("decoded_frames", "eval_rows", "h2d_bytes",
                "eval_queue", "devices"):
        assert key in wn
    # per-device map carries the memory columns (ledger staged on the
    # virtual chips; HBM keys present, zero-valued on CPU)
    assert wn["devices"], wn
    dev = next(iter(wn["devices"].values()))
    assert set(dev) >= {"tasks", "busy_seconds", "hbm_bytes_in_use",
                        "hbm_limit_bytes", "ledger_live_bytes"}

    # the human table grew the memory columns too
    r2 = subprocess.run(
        [sys.executable, tool, "--master", addr, "--once"],
        env=env, capture_output=True, text=True, timeout=180)
    assert r2.returncode == 0, r2.stderr
    assert "HBM MB" in r2.stdout and "LEDG MB" in r2.stdout


def test_local_pipeline_leaves_no_ledger_leaks(tmp_path, monkeypatch,
                                               ledger_leak_guard):
    """The opt-in leak guard over a real local pipeline with device
    staging forced: every buffer the engine registered during the run
    must be released once results are consumed."""
    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    from scanner_tpu import video as scv
    import scanner_tpu.kernels  # noqa: F401

    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=12)
    sc = Client(db_path=str(tmp_path / "db"))
    try:
        sc.ingest_videos([("leak_vid", vid)])
        frame = sc.io.Input([NamedVideoStream(sc, "leak_vid")])
        h = sc.ops.Histogram(frame=frame)
        out = NamedStream(sc, "leak_out")
        sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        rows = list(out.load())
        assert len(rows) == N_FRAMES
        # staging actually happened — the guard must not pass vacuously
        assert _counter("scanner_tpu_ledger_allocs_total") > 0
    finally:
        sc.stop()


# ---------------------------------------------------------------------------
# retention / compaction (satellite: last-N-bulks ring)
# ---------------------------------------------------------------------------

def test_history_compaction_keeps_stragglers_and_status(tmp_path):
    """Bulks aging out of the SPAN_HISTORY_BULKS ring drop their span
    stores and per-task scheduling state but keep straggler aggregates
    and a frozen status — GetJobStatus/GetTrace answer for the whole
    history, degrading (spans only) past the ring."""
    from scanner_tpu.engine.service import (SPAN_HISTORY_BULKS, Master,
                                            _BulkJob)

    master = Master(db_path=str(tmp_path / "db"), no_workers_timeout=5.0)
    try:
        n = SPAN_HISTORY_BULKS + 2
        for i in range(n):
            b = _BulkJob(bulk_id=i, spec_blob=b"", task_timeout=0.0,
                         trace_id=f"{i:032x}")
            b.job_tasks[0] = {(0, 0), (0, 1)}
            b.task_rows = {(0, 0): 8, (0, 1): 8}
            b.total_tasks = 2
            b.done = {(0, 0), (0, 1)}
            b.job_done[0] = 2
            b.stage_rows = {"load": 16, "evaluate": 16, "save": 16}
            for t in range(2):
                master._absorb_span_locked(b, {
                    "name": "task", "trace_id": b.trace_id,
                    "span_id": f"{t:016x}", "parent_id": None,
                    "start": 1.0, "end": 2.0 + t, "node": "worker0",
                    "attrs": {"job": 0, "task": t}})
            b.mark_finished()
            with master._lock:
                master._history[i] = b
        with master._lock:
            master._trim_history_locked()
            old = master._history[0]
            recent = master._history[n - 1]
        assert old.compacted and old.spans == [] and old.done == set()
        assert not recent.compacted and len(recent.spans) == 2

        # frozen status still serves, with live worker liveness
        st = master._rpc_job_status({"bulk_id": 0})
        assert st["finished"] and st["tasks_done"] == 2 \
            and st["total_tasks"] == 2
        assert st["num_workers"] == 0
        # straggler aggregates survive compaction; the span store does
        # not (drops are counted, not silent)
        tr = master._rpc_get_trace({"bulk_id": 0})
        assert tr["spans"] == []
        assert tr["stragglers"]["per_stage"]["task"]["count"] == 2
        assert tr["stragglers"]["slowest_tasks"]
        # late-arriving spans for a compacted bulk count as drops but
        # still feed the retained aggregates
        with master._lock:
            master._absorb_span_locked(old, {
                "name": "task", "trace_id": old.trace_id,
                "span_id": "f" * 16, "parent_id": None,
                "start": 1.0, "end": 9.0, "node": "worker0",
                "attrs": {"job": 0, "task": 5}})
        tr2 = master._rpc_get_trace({"bulk_id": 0})
        assert tr2["spans"] == [] and tr2["spans_dropped"] >= 1
        assert tr2["stragglers"]["per_stage"]["task"]["count"] == 3
        # a bulk inside the ring keeps everything
        tr3 = master._rpc_get_trace({"bulk_id": n - 1})
        assert len(tr3["spans"]) == 2
    finally:
        master.stop()


# ---------------------------------------------------------------------------
# structured logging (satellite: SCANNER_TPU_LOG_FORMAT=json)
# ---------------------------------------------------------------------------

def test_json_log_format_carries_trace_context():
    from scanner_tpu.util.log import JsonFormatter

    fmt = JsonFormatter()
    rec = logging.LogRecord("scanner_tpu.worker", logging.WARNING,
                            __file__, 1, "task %d requeued", (7,), None)
    out = json.loads(fmt.format(rec))
    assert out["level"] == "WARNING"
    assert out["logger"] == "scanner_tpu.worker"
    assert out["msg"] == "task 7 requeued"
    assert "trace_id" not in out  # outside any span

    tracer = _tr.default_tracer()
    with _tr.start_span(tracer, "task", job=1, task=2) as span:
        out2 = json.loads(fmt.format(rec))
        assert out2["trace_id"] == span.trace_id
        assert out2["span_id"] == span.span_id

    # exceptions serialize into the object, still one line
    try:
        raise ValueError("boom")
    except ValueError:
        rec_exc = logging.LogRecord("scanner_tpu.engine", logging.ERROR,
                                    __file__, 1, "failed", (),
                                    sys.exc_info())
    out3 = json.loads(fmt.format(rec_exc))
    assert "ValueError: boom" in out3["exc"]
    # newlines in the traceback are escaped: still one object per line
    assert len(fmt.format(rec_exc).splitlines()) == 1


def test_json_log_format_env_selects_handler(monkeypatch):
    """SCANNER_TPU_LOG_FORMAT=json makes the default stderr handler a
    JsonFormatter (fresh-configuration path)."""
    import scanner_tpu.util.log as log_mod

    root = logging.getLogger("scanner_tpu")
    top = logging.getLogger()  # pytest hangs capture handlers here;
    saved_handlers = root.handlers[:]  # _configure_once treats any
    saved_top = top.handlers[:]        # root handler as "app-managed"
    saved_configured = log_mod._configured
    try:
        root.handlers = []
        top.handlers = []
        log_mod._configured = False
        monkeypatch.setenv("SCANNER_TPU_LOG_FORMAT", "json")
        log_mod.get_logger("probe")
        assert root.handlers, "handler not installed"
        assert isinstance(root.handlers[0].formatter,
                          log_mod.JsonFormatter)
    finally:
        root.handlers = saved_handlers
        top.handlers = saved_top
        log_mod._configured = saved_configured
