"""Tutorial smoke tests (reference py_test.py test_tutorial): run example
scripts as subprocesses against a synthesized clip so they cannot rot."""

import os
import subprocess
import sys

import pytest

from scanner_tpu import video as scv

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every example runs (reference py_test.py test_tutorial covers the full
# tutorial set); each subprocess pays a full jax import + jit compile, so
# the clips are small
EXAMPLES = [
    "00_basic.py",
    "01_custom_ops.py",
    "02_op_attributes.py",
    "03_sampling.py",
    "04_slicing.py",
    "05_files_source_sink.py",
    "06_compression.py",
    "07_profiling.py",
    "08_distributed.py",
    "09_native_ops.py",
    "10_native_source_sink.py",
    "pose_detection.py",
    "reid_features.py",
    "shot_detection.py",
    "object_detection.py",
    "face_detection.py",
    "instance_segmentation.py",
    "grayscale_conversion.py",
    "optical_flow.py",
    "reverse_image_search.py",
    "hyperlapse.py",
]

# examples that run with NO arguments: they build their own inputs
# (synthesized scene videos with recall assertions, or a packed binary
# container) and assert results internally
SELF_CONTAINED = {"object_detection.py", "face_detection.py",
                  "instance_segmentation.py",
                  "10_native_source_sink.py"}


@pytest.fixture(scope="module")
def clip(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("ex") / "clip.mp4")
    scv.synthesize_video(p, num_frames=48, width=64, height=48, fps=24)
    return p


@pytest.mark.slow
@pytest.mark.parametrize("example", EXAMPLES)
def test_example_runs(example, clip, tmp_path):
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    args = [sys.executable, os.path.join(REPO, "examples", example)]
    if example in SELF_CONTAINED:
        pass  # no args: builds its own inputs, asserts internally
    elif example == "pose_detection.py":
        args += [clip, "5"]  # stride (it makes its own temp db)
    else:
        # hermetic per-test database
        args += [clip, str(tmp_path / "db")]
    r = subprocess.run(args, env=env, capture_output=True, text=True,
                       timeout=240)
    assert r.returncode == 0, f"{example} failed:\n{r.stdout}\n{r.stderr}"
