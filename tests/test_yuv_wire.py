"""YUV420 wire format: decode at 1.5 B/px, convert to RGB on the device.

The h2d halving of PERF.md §1 (reference analog: NV12 shipped to the GPU
and converted by scanner/util/image.cu:22).  Pinned here:
  - device and host converters are bit-identical (integer fixed point)
  - YUV-decoded + converted frames agree with the swscale RGB24 decode
    within chroma-interpolation tolerance and carry the same semantics
  - the ENGINE path (SCANNER_TPU_YUV_DEVICE=force on the CPU mesh) is
    bit-identical to the host-converted reference, including through
    samplers/gathers operating on the flat wire rows
"""

import os
import tempfile

import numpy as np
import pytest

from scanner_tpu import video as scv
from scanner_tpu.kernels.color import yuv420_to_rgb_device, yuv420_to_rgb_host
from scanner_tpu.video.lib import yuv420_frame_bytes


@pytest.fixture(scope="module")
def clip(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("yuvclip") / "clip.mp4")
    scv.synthesize_video(p, num_frames=48, width=128, height=96, fps=24,
                         keyint=8)
    return p


def test_converters_bit_exact_all_geometries():
    rng = np.random.RandomState(7)
    for h, w in [(96, 128), (97, 129), (33, 31), (480, 640)]:
        flat = rng.randint(0, 256, (3, yuv420_frame_bytes(h, w)), np.uint8)
        host = yuv420_to_rgb_host(flat, h, w)
        dev = np.asarray(yuv420_to_rgb_device(flat, h, w))
        assert host.shape == (3, h, w, 3)
        assert (host == dev).all(), f"device/host mismatch at {h}x{w}"


def test_yuv_decode_matches_sws_decode(tmp_db, clip):
    """Same frames decoded both ways: planar YUV + our fixed-point
    conversion vs swscale's packed RGB24.  The two conversions differ in
    chroma interpolation (nearest vs bilinear) and rounding, so equality
    is tolerance-based; the per-frame pattern id must survive exactly."""
    _, failed = scv.ingest_videos(tmp_db, [("c", clip)])
    assert not failed
    rows = [0, 7, 8, 23, 47]
    rgb = scv.load_frames(tmp_db, "c", rows)

    from scanner_tpu.storage import metadata as md
    from scanner_tpu.video.automata import DecoderAutomata
    desc = tmp_db.table_descriptor("c")
    vd = scv.load_video_meta(tmp_db, "c")
    a = DecoderAutomata(tmp_db.backend, vd,
                        md.column_item_path(desc.id, "frame", 0),
                        output_format="yuv420")
    try:
        flat = a.get_frames(rows)
    finally:
        a.close()
    assert flat.shape == (len(rows), yuv420_frame_bytes(96, 128))
    conv = yuv420_to_rgb_host(flat, 96, 128)
    diff = np.abs(conv.astype(int) - rgb.astype(int))
    assert diff.mean() < 3.0, f"mean diff {diff.mean():.2f}"
    assert np.percentile(diff, 99) <= 12, \
        f"p99 diff {np.percentile(diff, 99)}"
    for f, r in zip(conv, rows):
        assert scv.frame_pattern_id(f) == r % 14


def test_full_range_stream_not_plane_copied(tmp_db, tmp_path):
    """mjpeg decodes to FULL-range 4:2:0 (yuvj420p); a verbatim plane
    copy would feed full-range values into the limited-range on-device
    converter and stretch every tone.  The C layer must route full-range
    frames through swscale's range compression, keeping the YUV wire
    within tolerance of the RGB24 decode."""
    from scanner_tpu.storage import metadata as md
    from scanner_tpu.video.automata import DecoderAutomata

    from scanner_tpu.video.ingest import encode_frames_mp4

    p = str(tmp_path / "mj.mp4")
    try:
        encode_frames_mp4(
            p, (scv.frame_pattern(i, 96, 128) for i in range(8)),
            128, 96, codec="mjpeg")
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"mjpeg encoder unavailable: {e}")
    _, failed = scv.ingest_videos(tmp_db, [("mj", p)])
    assert not failed
    rows = list(range(8))
    rgb = scv.load_frames(tmp_db, "mj", rows)
    desc = tmp_db.table_descriptor("mj")
    vd = scv.load_video_meta(tmp_db, "mj")
    a = DecoderAutomata(tmp_db.backend, vd,
                        md.column_item_path(desc.id, "frame", 0),
                        output_format="yuv420")
    try:
        flat = a.get_frames(rows)
    finally:
        a.close()
    conv = yuv420_to_rgb_host(flat, 96, 128)
    diff = np.abs(conv.astype(int) - rgb.astype(int))
    # an unconverted full-range plane copy shows mean diff > 10 here
    assert diff.mean() < 4.0, f"full-range handling broken: {diff.mean()}"


def test_engine_yuv_wire_bit_exact(monkeypatch, tmp_path):
    """Engine run with the YUV wire forced on the CPU mesh: results are
    bit-identical to numpy histograms over host-converted YUV frames —
    the wire format changes bytes-on-the-link, never results."""
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    import scanner_tpu.kernels  # noqa: F401
    from scanner_tpu.storage import metadata as md
    from scanner_tpu.video.automata import DecoderAutomata

    monkeypatch.setenv("SCANNER_TPU_YUV_DEVICE", "force")
    root = tempfile.mkdtemp(prefix="yuvwire_")
    vid = os.path.join(root, "v.mp4")
    scv.synthesize_video(vid, num_frames=40, width=128, height=96, fps=24,
                         keyint=8)
    sc = Client(db_path=os.path.join(root, "db"))
    try:
        sc.ingest_videos([("t", vid)])
        # stride sampler exercises row gathers on the FLAT wire rows
        frames = sc.io.Input([NamedVideoStream(sc, "t")])
        strided = sc.streams.Stride(frames, [2])
        out = NamedStream(sc, "h")
        sc.run(sc.io.Output(sc.ops.Histogram(frame=strided), [out]),
               PerfParams.manual(8, 16), cache_mode=CacheMode.Overwrite,
               show_progress=False)
        got = np.stack(list(out.load()))

        desc = sc._db.table_descriptor("t")
        vd = scv.load_video_meta(sc._db, "t")
        a = DecoderAutomata(sc._db.backend, vd,
                            md.column_item_path(desc.id, "frame", 0),
                            output_format="yuv420")
        try:
            flat = a.get_frames(list(range(0, 40, 2)))
        finally:
            a.close()
        ref_frames = yuv420_to_rgb_host(flat, 96, 128)
        v = (ref_frames >> 4).astype(np.int32)
        expect = np.stack([
            np.stack([np.bincount(v[i, :, :, c].ravel(), minlength=16)
                      for c in range(3)])
            for i in range(v.shape[0])]).astype(got.dtype)
        assert got.shape == expect.shape
        assert (got == expect).all(), "engine YUV path altered results"
    finally:
        sc.stop()


def test_engine_yuv_off_uses_sws(monkeypatch, tmp_path):
    """SCANNER_TPU_YUV_DEVICE=0 keeps the classic RGB24 decode: results
    match numpy histograms over swscale-decoded frames."""
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    import scanner_tpu.kernels  # noqa: F401

    monkeypatch.setenv("SCANNER_TPU_YUV_DEVICE", "0")
    root = tempfile.mkdtemp(prefix="yuvoff_")
    vid = os.path.join(root, "v.mp4")
    scv.synthesize_video(vid, num_frames=16, width=64, height=48, fps=24)
    sc = Client(db_path=os.path.join(root, "db"))
    try:
        sc.ingest_videos([("t", vid)])
        frames = sc.io.Input([NamedVideoStream(sc, "t")])
        out = NamedStream(sc, "h")
        sc.run(sc.io.Output(sc.ops.Histogram(frame=frames), [out]),
               PerfParams.manual(8, 16), cache_mode=CacheMode.Overwrite,
               show_progress=False)
        got = np.stack(list(out.load()))
        rgb = scv.load_frames(sc._db, "t", list(range(16)))
        v = (rgb >> 4).astype(np.int32)
        expect = np.stack([
            np.stack([np.bincount(v[i, :, :, c].ravel(), minlength=16)
                      for c in range(3)])
            for i in range(v.shape[0])]).astype(got.dtype)
        assert (got == expect).all()
    finally:
        sc.stop()
