"""scanner-model: the bounded-interleaving protocol checker.

Three layers:
  * the real model — every scenario explores EXHAUSTIVELY (no bound
    truncation) with all three invariants holding at every reachable
    state, over a non-trivial schedule count;
  * teeth — each injected defect (`broken=`) is found, with a short
    minimal counterexample schedule (BFS order guarantees minimality);
  * the CLI — exit codes and JSON shape tools and CI consume.

The model itself is pinned to the engine by scanner-check SC406
(tests/test_static_analysis.py::test_real_model_anchoring_is_live).
"""

import json
import os
import subprocess
import sys

import pytest

from scanner_tpu.analysis.model import (RPC_ANCHORS, SCENARIOS,
                                        explore_scenario, lineage,
                                        scenario)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the real model holds
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_exhaustive_and_clean(name):
    r = explore_scenario(name)
    assert r.ok, r.violation.format()
    assert r.exhausted, \
        f"{name}: depth/state bound truncated the exploration — " \
        "the invariant claim only covers what was enumerated"
    assert r.states > 100, \
        f"{name}: only {r.states} states — the scenario degenerated"
    assert r.schedules > 500, \
        f"{name}: only {r.schedules} interleavings enumerated"


def test_failover_explores_enough_interleavings():
    """The headline scenario (two masters racing a generation bump,
    worker retrying a non-idempotent RPC): exhaustive over the 1e4–1e5
    interleaving range the design targets."""
    r = explore_scenario("failover")
    assert r.exhausted and r.ok
    assert r.schedules >= 10_000


# ---------------------------------------------------------------------------
# teeth: injected defects are found, minimally
# ---------------------------------------------------------------------------

def test_ack_before_commit_found_with_minimal_trace():
    r = explore_scenario("crash", broken="ack_before_commit")
    assert not r.ok
    v = r.violation
    assert v.invariant == "I1-write-ahead"
    assert "ACKED" in v.detail
    # minimal: register -> admit -> assign -> ack-before-commit; BFS
    # cannot reach the bad state in fewer steps
    assert len(v.trace) == 4, v.format()
    assert "before the commit" in v.trace[-1]


def test_skip_dedup_found_via_retry():
    r = explore_scenario("failover", broken="skip_dedup")
    assert not r.ok
    v = r.violation
    assert v.invariant == "I2-no-double-apply"
    assert "TWO done-records" in v.detail
    # the counterexample must actually involve a lost ack + retry
    assert any("ack is lost" in s for s in v.trace), v.format()
    assert len(v.trace) <= 6


def test_ignore_fence_found_in_failover_and_gang():
    r = explore_scenario("failover", broken="ignore_fence")
    assert not r.ok
    assert r.violation.invariant == "I3-fencing"
    assert "fence" in r.violation.detail
    g = explore_scenario("gang", broken="ignore_fence")
    assert not g.ok
    assert g.violation.invariant == "I3-fencing"
    assert "straggler" in g.violation.detail


def test_violating_state_is_reproducible():
    """Replaying the reported schedule from the initial state lands on
    the violating state — the trace is a real schedule, not a path
    summary."""
    from scanner_tpu.analysis.model import enabled
    cfg, state = scenario("crash", broken="ack_before_commit")
    r = explore_scenario("crash", broken="ack_before_commit")
    for step in r.violation.trace:
        nxt = dict(enabled(state, cfg))
        assert step in nxt, f"step {step!r} not enabled"
        state = nxt[step]
    assert state == r.violation.state


# ---------------------------------------------------------------------------
# model internals the invariants rely on
# ---------------------------------------------------------------------------

def test_lineage_is_snapshot_plus_own_segment():
    cfg, s = scenario("failover")
    assert lineage(s) == ()
    # m0 journals one record; before failover the lineage is m0's
    from scanner_tpu.analysis.model import enabled

    def step(s, needle):
        for label, ns in enabled(s, cfg):
            if needle in label:
                return ns
        raise AssertionError(f"no enabled step matching {needle!r}")

    s = step(s, "worker registers with m0")
    s = step(s, "m0 admits")
    assert [t for t, *_ in lineage(s)] == ["admit"]
    # after m1 claims + recovers, the lineage is the takeover snapshot
    s = step(s, "m1 claims")
    s = step(s, "m1 recovers")
    assert [t for t, *_ in lineage(s)] == ["admit"]


def test_anchors_match_transitions():
    """Every anchor key names a defined t_<key> (the SC406 convention,
    checked here without the analyzer so a bare pytest run fails too)."""
    from scanner_tpu.analysis.model import protocol
    for key in RPC_ANCHORS:
        assert callable(getattr(protocol, f"t_{key}", None)), key


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _run_model(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scanner_model.py"),
         *args],
        capture_output=True, text=True, env=env, timeout=300)


def test_cli_all_scenarios_pass():
    r = _run_model("--json")
    assert r.returncode == 0, r.stdout + r.stderr
    docs = json.loads(r.stdout)
    assert {d["scenario"] for d in docs} == set(SCENARIOS)
    assert all(d["ok"] and d["exhausted"] for d in docs)


def test_cli_broken_exits_nonzero_with_trace():
    r = _run_model("--scenario", "crash", "--broken",
                   "ack_before_commit")
    assert r.returncode == 1
    assert "INVARIANT VIOLATED: I1-write-ahead" in r.stdout
    assert "minimal schedule" in r.stdout


def test_cli_truncation_exits_two():
    r = _run_model("--scenario", "surface", "--max-states", "50")
    assert r.returncode == 2
    assert "TRUNCATED" in r.stdout
