"""Standalone master process for restart-recovery tests (the reference
master is its own process too; recover_and_init_database master.cpp:1311).

Usage: python spawn_master.py <db_path> <port> [shard_id num_shards]

The optional shard args spawn one shard of a horizontally sharded
control plane (docs/robustness.md §Sharded control plane): the process
claims generations in shard <shard_id>'s namespace and registers its
address in the durable shard map.
"""

import sys

from scanner_tpu.engine.service import start_master

if __name__ == "__main__":
    db_path = sys.argv[1]
    port = int(sys.argv[2])
    kw = {}
    if len(sys.argv) > 4:
        kw["shard_id"] = int(sys.argv[3])
        kw["num_shards"] = int(sys.argv[4])
    start_master(db_path, port=port, no_workers_timeout=60.0, block=True,
                 **kw)
