"""Standalone master process for restart-recovery tests (the reference
master is its own process too; recover_and_init_database master.cpp:1311).

Usage: python spawn_master.py <db_path> <port>
"""

import sys

from scanner_tpu.engine.service import start_master

if __name__ == "__main__":
    db_path = sys.argv[1]
    port = int(sys.argv[2])
    start_master(db_path, port=port, no_workers_timeout=60.0, block=True)
