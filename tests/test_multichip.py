"""Multi-chip evaluator affinity (engine/evaluate.py assigned_device &
friends): per-device pipeline instances + async sink fetch.

Four contracts pinned here:

1. **Virtual multi-device equivalence** — the same bulk runs on a 1- and
   a 4-device virtual host (XLA host platform devices +
   SCANNER_TPU_KERNEL_DEVICES=all, the same lever the dp-shard path
   uses) produce bit-exact outputs for stateless, stencil,
   stateful-chain and null-interleaved pipelines.
2. **Spread + ladder bound** — on the 4-device host, tasks land on >= 2
   distinct chips (per-device task counters) and each (op, device)'s
   distinct-executable count stays within the PR 2 bucket-ladder bound;
   SCANNER_TPU_DEVICE_AFFINITY=0 restores default-chip dispatch (the
   A/B lever) with identical results.
3. **Assignment plumbing** — instance i of P owns chip i mod n; the
   stateful-chain path keeps everything on one instance's chip;
   pipeline_instances_per_node defaults to the device count only on
   multi-device hosts.
4. **Async sink fetch ordering** — results are identical whether the
   sink d2h copy was prefetched at eval-done or only happens after the
   saver dequeues (SCANNER_TPU_ASYNC_SINK_FETCH=0).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from scanner_tpu.engine.evaluate import bucket_ladder
from scanner_tpu.util.jaxenv import cpu_only_env

HERE = os.path.dirname(os.path.abspath(__file__))
RUNNER = os.path.join(HERE, "multichip_runner.py")
N_FRAMES = 64
W, H = 64, 48
WP = 8  # runner's work packet: ladder is bucket_ladder(8)


@pytest.fixture(scope="module")
def video(tmp_path_factory):
    from scanner_tpu import video as scv
    root = tmp_path_factory.mktemp("multichip")
    vid = str(root / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=W, height=H,
                         fps=24, keyint=16)
    return vid


def _spawn(video, tmp_path, n_devices):
    out = str(tmp_path / f"mc_{n_devices}.json")
    env = cpu_only_env(n_devices=n_devices)
    # script-by-path puts tests/ (not the repo root) on sys.path
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["SCANNER_TPU_KERNEL_DEVICES"] = "all"
    env.pop("SCANNER_TPU_DEVICE_AFFINITY", None)
    env.pop("SCANNER_TPU_BUCKETED", None)
    r = subprocess.run(
        [sys.executable, RUNNER, video, out],
        env=env, cwd=os.path.dirname(HERE), capture_output=True,
        text=True, timeout=900)
    assert r.returncode == 0 and "MULTICHIP_OK" in r.stdout, \
        f"runner failed (rc={r.returncode}):\n{r.stderr[-3000:]}"
    with open(out) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def single(video, tmp_path_factory):
    return _spawn(video, tmp_path_factory.mktemp("mc1"), 1)


@pytest.fixture(scope="module")
def quad(video, tmp_path_factory):
    return _spawn(video, tmp_path_factory.mktemp("mc4"), 4)


def test_virtual_hosts_have_expected_devices(single, quad):
    assert single["n_devices"] == 1
    assert quad["n_devices"] == 4


@pytest.mark.parametrize("pipeline",
                         ["hist", "stencil", "chain", "nulls"])
def test_bit_exact_across_device_counts(single, quad, pipeline):
    """Outputs of the 4-device run are bit-exact vs the 1-device run —
    per-chip staging, per-chip executables and round-robin task
    assignment change WHERE work runs, never what it computes."""
    a = single["runs"][pipeline]["rows"]
    b = quad["runs"][pipeline]["rows"]
    assert a == b
    assert len(a) > 0


def test_tasks_spread_across_devices(quad):
    """The 4-device bulk really uses multiple chips: the per-device task
    counters (scanner_tpu_device_tasks_total) climb on >= 2 distinct
    non-default devices during the stateless run (4 tasks round-robin
    onto 4 instances)."""
    delta = quad["runs"]["hist"]["device_tasks_delta"]
    used = {k for k, v in delta.items()
            if v > 0 and "default" not in k}
    assert len(used) >= 2, delta


def test_stateful_chain_stays_on_one_chip(quad):
    """PR 2 invariant carried forward: a stateful-affinity chain
    serializes onto one instance and therefore one chip."""
    delta = quad["runs"]["chain"]["device_tasks_delta"]
    used = {k for k, v in delta.items() if v > 0}
    assert len(used) == 1, delta


def test_per_device_recompiles_within_ladder(quad):
    """Each (op, device)'s distinct-executable delta for one bulk stays
    within the op's bucket ladder — the PR 2 CI guard, now holding PER
    CHIP (the recompile proxy keys on (device, shape, dtype))."""
    ladder = len(bucket_ladder(WP))
    delta = quad["runs"]["hist"]["recompiles_delta"]
    hist = {k: v for k, v in delta.items() if "Histogram" in k}
    assert hist, delta
    for labels, count in hist.items():
        assert 0 <= count <= ladder, (labels, count, delta)


def test_affinity_kill_switch_restores_default_dispatch(single, quad):
    """SCANNER_TPU_DEVICE_AFFINITY=0 on the 4-device host: every task
    evaluates under the "default" device label (no per-chip pinning)
    and results stay identical — the acceptance A/B lever."""
    na = quad["runs"]["hist_no_affinity"]
    used = {k for k, v in na["device_tasks_delta"].items() if v > 0}
    assert used and all("default" in k for k in used), used
    assert na["rows"] == single["runs"]["hist"]["rows"]


# ---------------------------------------------------------------------------
# in-process unit coverage: assignment mapping + async sink fetch
# ---------------------------------------------------------------------------

def test_assigned_device_mapping(monkeypatch):
    """instance i of P owns chip i mod n; partitions are disjoint and
    cover the host; single instance keeps the whole dp-shard set."""
    import scanner_tpu.engine.evaluate as ev

    class _Dev:
        def __init__(self, i):
            self.id = i
            self.platform = "cpu"

        def __repr__(self):
            return f"dev{self.id}"

    devs = [_Dev(i) for i in range(4)]
    monkeypatch.setattr(ev, "kernel_devices", lambda: list(devs))
    monkeypatch.delenv("SCANNER_TPU_DEVICE_AFFINITY", raising=False)
    assert [ev.assigned_device(i) for i in range(4)] == devs
    assert ev.assigned_device(5) is devs[1]  # i mod n
    # partitions: disjoint, cover all chips, lead with the owned chip
    parts = [ev.instance_devices(i, 2) for i in range(2)]
    assert parts[0][0] is devs[0] and parts[1][0] is devs[1]
    flat = [d for p in parts for d in p]
    assert sorted(d.id for d in flat) == [0, 1, 2, 3]
    # one instance: whole host (model kernels keep dp-sharding it all)
    assert ev.instance_devices(0, 1) == devs
    # instance-count default: device count only when UNSET; an explicit
    # value — including 1 (memory bound / serialized evaluation) — wins
    assert ev.default_pipeline_instances(None) == 4
    assert ev.default_pipeline_instances(0) == 4
    assert ev.default_pipeline_instances(1) == 1
    assert ev.default_pipeline_instances(2) == 2
    # kill switch: no pinning, no device-count default
    monkeypatch.setenv("SCANNER_TPU_DEVICE_AFFINITY", "0")
    assert ev.assigned_device(0) is None
    assert ev.default_pipeline_instances(None) == 1
    assert ev.device_label(None) == "default"
    assert ev.device_label(devs[2]) == "cpu:2"


def _run_hist(sc, name, rows=24):
    from scanner_tpu import CacheMode, NamedStream, NamedVideoStream, \
        PerfParams
    frame = sc.io.Input([NamedVideoStream(sc, "af")])
    ranged = sc.streams.Range(frame, [(0, rows)])
    out = NamedStream(sc, name)
    sc.run(sc.io.Output(sc.ops.Histogram(frame=ranged), [out]),
           PerfParams.manual(8, 16), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    return list(out.load())


@pytest.fixture()
def af_client(tmp_path):
    from scanner_tpu import Client
    from scanner_tpu import video as scv
    import scanner_tpu.kernels  # noqa: F401
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=24, width=W, height=H, fps=24)
    sc = Client(db_path=str(tmp_path / "db"))
    sc.ingest_videos([("af", vid)])
    yield sc
    sc.stop()


def test_async_sink_fetch_ordering(af_client, monkeypatch):
    """Async-fetch A/B: with the prefetch disabled the d2h only happens
    after the saver dequeues — results must be identical either way, and
    the prefetch hook must actually fire on device-staged sink batches
    when enabled (in-process via the 1-device virtual staging path)."""
    from scanner_tpu.engine.batch import ColumnBatch

    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    calls = []
    orig = ColumnBatch.prefetch_host

    def spy(self):
        calls.append(type(self.data).__module__)
        return orig(self)

    monkeypatch.setattr(ColumnBatch, "prefetch_host", spy)
    monkeypatch.setenv("SCANNER_TPU_ASYNC_SINK_FETCH", "1")
    rows_async = _run_hist(af_client, "af_async")
    assert calls, "prefetch_host never fired with async fetch enabled"
    n_async = len(calls)

    # fetch-after-dequeue ordering: the saver pulls the task before any
    # copy was started; correctness must not depend on the prefetch
    monkeypatch.setenv("SCANNER_TPU_ASYNC_SINK_FETCH", "0")
    rows_sync = _run_hist(af_client, "af_sync")
    assert len(calls) == n_async, "prefetch fired despite opt-out"

    assert len(rows_async) == len(rows_sync) == 24
    for a, b in zip(rows_async, rows_sync):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_prefetch_host_is_safe_on_host_data():
    """prefetch_host is a no-op (not an error) for host batches and
    returns self for chaining."""
    from scanner_tpu.engine.batch import ColumnBatch
    b = ColumnBatch(np.arange(4), np.zeros((4, 3), np.uint8))
    assert b.prefetch_host() is b
    lst = ColumnBatch(np.arange(2), [b"x", b"y"])
    assert lst.prefetch_host() is lst


def test_to_device_targets_explicit_device(monkeypatch):
    """ColumnBatch.to_device(device=...) commits the batch to the named
    chip (the satellite: staging must never rely on the implicit
    default device); re-staging to the same chip is a no-op."""
    import jax
    dev = jax.local_devices()[0]
    from scanner_tpu.engine.batch import ColumnBatch
    b = ColumnBatch(np.arange(4), np.arange(12, dtype=np.uint8
                                            ).reshape(4, 3))
    d = b.to_device(dev)
    assert set(d.data.devices()) == {dev}
    assert d.to_device(dev) is d  # already there: no copy
    back = d.to_host()
    assert np.array_equal(back.data, b.data)
