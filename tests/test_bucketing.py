"""Shape-stable kernel execution (engine/evaluate.py bucketed dispatch).

Three contracts are pinned here:

1. **Padding equivalence** — bucketed execution (pad tail chunks up to a
   power-of-two bucket, mask null rows through the call) is bit-identical
   to exact-shape execution for stateless, stencil, multi-output,
   stateful and null-interleaved kernels, across bucket boundaries and
   for tasks smaller than the smallest bucket.
2. **Shape-churn regression guard** — on the golden pipeline, each
   stdlib device op's distinct input-signature count (the
   scanner_tpu_op_recompiles_total proxy) stays bounded by its
   bucket-ladder size.  A future ragged call path fails here instead of
   silently re-tracing on TPU, where every new signature is seconds of
   XLA compile.
3. **Contiguous-range fast path** — ColumnBatch.take_rows/take_range
   slice [start, end) ranges directly (views) and agree with the
   general gather, nulls included.
"""

from typing import Any, Sequence, Tuple

import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, DeviceType, FrameType, Kernel,
                         NamedStream, NamedVideoStream, NullElement,
                         PerfParams, register_op)
import scanner_tpu.kernels  # noqa: F401  (registers Histogram)
from scanner_tpu import video as scv
from scanner_tpu.engine.batch import ColumnBatch
from scanner_tpu.engine.evaluate import bucket_for, bucket_ladder
from scanner_tpu.util.metrics import registry

N_FRAMES = 50
W, H = 64, 48


@pytest.fixture(scope="module")
def sc(tmp_path_factory):
    root = tmp_path_factory.mktemp("bucketing")
    vid = str(root / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=W, height=H,
                         fps=24, keyint=12)
    client = Client(db_path=str(root / "db"))
    client.ingest_videos([("bk", vid)])
    yield client
    client.stop()


# ---------------------------------------------------------------------------
# ladder unit tests
# ---------------------------------------------------------------------------

def test_bucket_ladder_shape():
    assert bucket_ladder(1) == [1]
    assert bucket_ladder(4) == [4]
    assert bucket_ladder(6) == [4, 6]
    assert bucket_ladder(8) == [4, 8]
    assert bucket_ladder(16) == [4, 8, 16]
    assert bucket_ladder(100) == [4, 8, 16, 32, 64, 100]


def test_bucket_for_rounds_up():
    ladder = bucket_ladder(16)
    assert [bucket_for(k, ladder) for k in (1, 3, 4, 5, 8, 9, 16)] == \
        [4, 4, 4, 8, 8, 16, 16]


# ---------------------------------------------------------------------------
# padding-equivalence kernels (device-declared so the bucketed path
# engages; numpy-implemented so they run bit-exactly on the CPU backend)
# ---------------------------------------------------------------------------

@register_op(device=DeviceType.TPU, batch=16)
class BkStat(Kernel):
    """Stateless batched device kernel: per-row pixel sum."""

    calls: list = []  # batch sizes actually executed (shape probe)

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        BkStat.calls.append(len(frame))
        f = np.asarray(frame, np.int64)
        return f.reshape(len(f), -1).sum(axis=1)


@register_op(device=DeviceType.TPU, stencil=[-1, 0], batch=8)
class BkStencil(Kernel):
    """Stencil batched device kernel: sum over the 2-frame window."""

    def execute(self, frame: Sequence[Sequence[FrameType]]
                ) -> Sequence[Any]:
        a = np.asarray(frame, np.int64)  # (b, 2, H, W, C)
        return a.reshape(len(a), -1).sum(axis=1)


@register_op(device=DeviceType.TPU, batch=16)
class BkMulti(Kernel):
    """Multi-output batched device kernel: (array batch, per-row list)."""

    def execute(self, frame: Sequence[FrameType]) -> Tuple[Any, Any]:
        f = np.asarray(frame, np.int64)
        sums = f.reshape(len(f), -1).sum(axis=1)
        return sums, [int(s) % 251 for s in sums]


@register_op(device=DeviceType.TPU, batch=16, bounded_state=0)
class BkStateful(Kernel):
    """Stateful batched device kernel: running count across calls (the
    dispatcher must keep exact shapes here — padding rows would advance
    the count)."""

    def __init__(self, config):
        super().__init__(config)
        self._n = 0

    def reset(self):
        self._n = 0

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        out = [self._n + i for i in range(len(frame))]
        self._n += len(frame)
        return out


def _load(out):
    return list(out.load())


def _assert_rows_equal(a, b):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        if isinstance(x, NullElement) or isinstance(y, NullElement):
            assert isinstance(x, NullElement) \
                and isinstance(y, NullElement), i
        elif isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            assert np.array_equal(np.asarray(x), np.asarray(y)), i
        else:
            assert x == y, i


def _run_ab(sc, monkeypatch, build, name, wp=8, io=16):
    """Run the same graph with exact shapes and with bucketed dispatch;
    return (exact_rows, bucketed_rows)."""
    outs = {}
    for mode, flag in (("exact", "0"), ("bucketed", "1")):
        monkeypatch.setenv("SCANNER_TPU_BUCKETED", flag)
        frame = sc.io.Input([NamedVideoStream(sc, "bk")])
        col = build(frame)
        out = NamedStream(sc, f"bk_{name}_{mode}")
        sc.run(sc.io.Output(col, [out]), PerfParams.manual(wp, io),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        outs[mode] = _load(out)
    return outs["exact"], outs["bucketed"]


# rows counts straddle bucket boundaries: sub-smallest-bucket task (3),
# exact bucket (16), bucket+tail (21), full stream with ragged tail (50)
@pytest.mark.parametrize("rows", [3, 16, 21, N_FRAMES])
def test_padding_equivalence_stateless(sc, monkeypatch, rows):
    exact, bucketed = _run_ab(
        sc, monkeypatch,
        lambda f: sc.ops.BkStat(frame=sc.streams.Range(f, [(0, rows)])),
        f"stat{rows}")
    assert len(exact) == rows
    _assert_rows_equal(exact, bucketed)


def test_padding_pads_to_buckets(sc, monkeypatch):
    """The shape probe: bucketed execution only ever calls at ladder
    shapes; a 21-row task at wp=8 must not produce a 5-row call."""
    BkStat.calls = []
    monkeypatch.setenv("SCANNER_TPU_BUCKETED", "1")
    frame = sc.io.Input([NamedVideoStream(sc, "bk")])
    r = sc.streams.Range(frame, [(0, 21)])
    out = NamedStream(sc, "bk_probe")
    sc.run(sc.io.Output(sc.ops.BkStat(frame=r), [out]),
           PerfParams.manual(8, 16), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    ladder = set(bucket_ladder(8))  # BkStat cap 16, wp 8 -> cap 8
    assert BkStat.calls and set(BkStat.calls) <= ladder, BkStat.calls
    assert len(_load(out)) == 21


def test_padding_equivalence_stencil(sc, monkeypatch):
    exact, bucketed = _run_ab(
        sc, monkeypatch,
        lambda f: sc.ops.BkStencil(frame=sc.streams.Range(f, [(0, 21)])),
        "stencil", wp=8, io=24)
    _assert_rows_equal(exact, bucketed)


@pytest.mark.parametrize("col", ["output0", "output1"])
def test_padding_equivalence_multi_output(sc, monkeypatch, col):
    exact, bucketed = _run_ab(
        sc, monkeypatch,
        lambda f: sc.ops.BkMulti(
            frame=sc.streams.Range(f, [(0, 21)]))[col],
        f"multi_{col}")
    _assert_rows_equal(exact, bucketed)


def test_padding_equivalence_stateful(sc, monkeypatch):
    """Stateful kernels keep exact call shapes under bucketed dispatch
    (padding would advance their state) — outputs stay identical."""
    exact, bucketed = _run_ab(
        sc, monkeypatch,
        lambda f: sc.ops.BkStateful(
            frame=sc.streams.Range(f, [(0, 21)])),
        "stateful")
    _assert_rows_equal(exact, bucketed)
    assert exact == list(range(21))  # state really did run row-by-row


def test_padding_equivalence_null_interleaved(sc, monkeypatch):
    """Null rows ride through the bucketed call at the full chunk shape
    and come out as NullElement — bit-identical to the exact path's
    live-subset call."""
    def build(f):
        r = sc.streams.Range(f, [(0, 6)])
        spaced = sc.streams.RepeatNull(r, [3])  # 18 rows, 12 null
        return sc.ops.BkStat(frame=spaced)

    exact, bucketed = _run_ab(sc, monkeypatch, build, "nulls")
    assert sum(isinstance(e, NullElement) for e in exact) == 12
    _assert_rows_equal(exact, bucketed)


# ---------------------------------------------------------------------------
# shape-churn regression guard (CI): stdlib device ops on the golden
# pipeline stay within their bucket ladder
# ---------------------------------------------------------------------------

def _op_counter(series: str):
    # sum across the device label: multi-chip runs split an op's count
    # over per-device samples, and a stale single-sample read would
    # alias another device's (unchanging) value
    snap = registry().snapshot()
    out: dict = {}
    for s in snap.get(series, {}).get("samples", []):
        op = s["labels"]["op"]
        out[op] = out.get(op, 0) + s["value"]
    return out


def test_shape_churn_guard_golden_pipeline(sc, monkeypatch):
    """Golden tier-1 pipeline (CPU backend, jit enabled): per device op,
    the distinct input-signature count of a bulk run — the
    scanner_tpu_op_recompiles_total delta — must stay within the op's
    bucket-ladder size, whatever the task/null geometry.  Tail work
    packets (50 % 16 = 2-row task) and null-thinned chunks must NOT
    mint signatures."""
    monkeypatch.delenv("SCANNER_TPU_BUCKETED", raising=False)
    wp, io = 8, 16
    ladder_size = len(bucket_ladder(wp))  # Histogram cap 16, wp 8 -> 8
    before = _op_counter("scanner_tpu_op_recompiles_total")

    # run 1: ragged tail geometry (tasks of 16,16,16,2 rows)
    frame = sc.io.Input([NamedVideoStream(sc, "bk")])
    hist = sc.ops.Histogram(frame=frame)
    out1 = NamedStream(sc, "guard_hist")
    sc.run(sc.io.Output(hist, [out1]), PerfParams.manual(wp, io),
           cache_mode=CacheMode.Overwrite, show_progress=False)

    # run 2: null-interleaved geometry (21 rows, 14 of them null)
    frame = sc.io.Input([NamedVideoStream(sc, "bk")])
    spaced = sc.streams.RepeatNull(
        sc.streams.Range(frame, [(0, 7)]), [3])
    hist2 = sc.ops.Histogram(frame=spaced)
    out2 = NamedStream(sc, "guard_hist_null")
    sc.run(sc.io.Output(hist2, [out2]), PerfParams.manual(wp, io),
           cache_mode=CacheMode.Overwrite, show_progress=False)

    after = _op_counter("scanner_tpu_op_recompiles_total")
    for op in ("Histogram",):
        # each run builds a fresh evaluator (fresh signature set), so
        # the two runs may each contribute up to one ladder of sigs
        delta = after.get(op, 0) - before.get(op, 0)
        assert 0 < delta <= 2 * ladder_size, (
            f"{op}: {delta} distinct shape signatures across two runs "
            f"(bucket ladder size {ladder_size} per run) — a ragged "
            f"call path is re-tracing")
    # outputs stay correct under the guard geometry
    assert len(_load(out1)) == N_FRAMES
    rows2 = _load(out2)
    assert len(rows2) == 21
    assert sum(isinstance(e, NullElement) for e in rows2) == 14


def test_shape_churn_guard_fused_chains(sc, monkeypatch):
    """Fusion extension of the shape-churn guard (PERF.md §5 sweep, §8):
    on the golden fusable pipeline under the same ragged-tail +
    null-interleaved geometry sweep, (a) the fused chain's distinct
    input-signature count stays within ITS bucket ladder — chains obey
    the same ladder contract as single ops — and (b) the total number
    of executables minted across the graph strictly DECREASES fused vs
    staged: one program per chain rung replaces one per member per
    rung."""
    from scanner_tpu.graph import fusion

    monkeypatch.delenv("SCANNER_TPU_BUCKETED", raising=False)
    wp, io = 8, 16
    # HistDiff (windowed, non-head) stays staged and mints its own
    # ladder in BOTH modes; the chain covers the other three
    cid = "Resize+Blur+Histogram"
    members = ("Resize", "Blur", "Histogram", "HistDiff")

    def sweep(tag):
        """§5 ragged sweep: run 1 tail geometry (16,16,16,2 row tasks),
        run 2 null-interleaved (21 rows, 14 null)."""
        frame = sc.io.Input([NamedVideoStream(sc, "bk")])
        small = sc.ops.Resize(frame=frame, width=[32], height=[24])
        blur = sc.ops.Blur(frame=small, kernel_size=3, sigma=1.1)
        hist = sc.ops.Histogram(frame=blur)
        diff = sc.ops.HistDiff(frame=hist)
        sc.run(sc.io.Output(diff, [NamedStream(sc, f"guard_fz_{tag}1")]),
               PerfParams.manual(wp, io),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        frame = sc.io.Input([NamedVideoStream(sc, "bk")])
        spaced = sc.streams.RepeatNull(
            sc.streams.Range(frame, [(0, 7)]), [3])
        small = sc.ops.Resize(frame=spaced, width=[32], height=[24])
        blur = sc.ops.Blur(frame=small, kernel_size=3, sigma=1.1)
        hist = sc.ops.Histogram(frame=blur)
        diff = sc.ops.HistDiff(frame=hist)
        sc.run(sc.io.Output(diff, [NamedStream(sc, f"guard_fz_{tag}2")]),
               PerfParams.manual(wp, io),
               cache_mode=CacheMode.Overwrite, show_progress=False)

    def minted(before, after, keys):
        return sum(after.get(k, 0) - before.get(k, 0) for k in keys)

    prev = fusion.enabled()
    try:
        fusion.set_enabled(True)
        before = _op_counter("scanner_tpu_op_recompiles_total")
        sweep("fused")
        after = _op_counter("scanner_tpu_op_recompiles_total")
        chain_delta = after.get(cid, 0) - before.get(cid, 0)
        # each run builds a fresh evaluator, so two runs may each mint
        # up to one chain ladder (cap <= wp => ladder(cap) <= ladder(wp))
        ladder_size = len(bucket_ladder(wp))
        assert 0 < chain_delta <= 2 * ladder_size, (
            f"{cid}: {chain_delta} signatures across the sweep "
            f"(<= {2 * ladder_size} allowed) — the fused path is "
            f"re-tracing")
        fused_total = minted(before, after, (cid,) + members)

        fusion.set_enabled(False)
        before = _op_counter("scanner_tpu_op_recompiles_total")
        sweep("staged")
        after = _op_counter("scanner_tpu_op_recompiles_total")
        staged_total = minted(before, after, (cid,) + members)
    finally:
        fusion.set_enabled(prev)

    assert fused_total < staged_total, (
        f"fusion must strictly reduce minted executables: fused "
        f"{fused_total} vs staged {staged_total}")
    # fused outputs stay correct under the guard geometry (HistDiff's
    # [-1, 0] stencil nullifies every live row whose window touches a
    # null neighbor: of the 7 live rows only row 0 — REPEAT_EDGE-
    # clamped onto itself — survives)
    assert len(_load(NamedStream(sc, "guard_fz_fused1"))) == N_FRAMES
    rows2 = _load(NamedStream(sc, "guard_fz_fused2"))
    assert len(rows2) == 21
    assert sum(isinstance(e, NullElement) for e in rows2) == 20


def test_recompile_signature_includes_dtype(monkeypatch):
    """Two calls with equal shapes but different dtypes are distinct XLA
    executables — the recompile proxy must count both (it used to key on
    shape alone and undercount, e.g. uint8 vs float32 after a
    conversion)."""
    from scanner_tpu.engine.evaluate import TaskEvaluator
    from scanner_tpu.graph import analysis as A
    from scanner_tpu.graph import ops as O
    from scanner_tpu.graph.streams_dsl import IOGenerator
    from scanner_tpu.util.profiler import Profiler

    monkeypatch.setenv("SCANNER_TPU_BUCKETED", "1")
    monkeypatch.setenv("SCANNER_TPU_PRECOMPILE", "0")

    class _Src:
        is_video = False

    io_g = IOGenerator()
    frame = io_g.Input([_Src()])
    col = O.OpGenerator().BkStat(frame=frame)
    outp = io_g.Output(col, [_Src()])
    info = A.analyze([outp])
    src = info.sources[0]
    jr = A.job_rows(info, 0, {src.id: 8})
    jr.work_packet_size = 8
    plan = A.derive_task_streams(info, jr, (0, 8))
    te = TaskEvaluator(info, Profiler())
    try:
        before = _op_counter(
            "scanner_tpu_op_recompiles_total").get("BkStat", 0)
        rows = np.arange(8, dtype=np.int64)
        for dtype in (np.uint8, np.float32):
            batch = ColumnBatch(rows, np.zeros((8, 4, 4, 3), dtype))
            res = te.execute_task(jr, plan, {src.id: batch})
            assert all(len(b) == 8 for b in res.values())
        after = _op_counter(
            "scanner_tpu_op_recompiles_total").get("BkStat", 0)
        assert after - before == 2, (
            "equal shapes with different dtypes must count as two "
            "signatures")
    finally:
        te.close()


# ---------------------------------------------------------------------------
# ladder precompile (warm-up)
# ---------------------------------------------------------------------------

def test_precompile_warms_ladder(sc, monkeypatch):
    """SCANNER_TPU_PRECOMPILE=1 forces the setup-time ladder warm-up
    (CPU backend): every device op's ladder compiles on the background
    thread and the per-op precompile gauge appears."""
    from scanner_tpu.engine.evaluate import TaskEvaluator
    from scanner_tpu.graph import analysis as A
    from scanner_tpu.util.profiler import Profiler

    monkeypatch.setenv("SCANNER_TPU_PRECOMPILE", "1")
    monkeypatch.delenv("SCANNER_TPU_BUCKETED", raising=False)
    frame = sc.io.Input([NamedVideoStream(sc, "bk")])
    hist = sc.ops.Histogram(frame=frame)
    outp = sc.io.Output(hist, [NamedStream(sc, "warm_direct")])
    info = A.analyze([outp])
    te = TaskEvaluator(info, Profiler(), precompile=(H, W, 8))
    try:
        assert te._precompile_thread is not None
        te._precompile_thread.join(timeout=60)
        assert not te._precompile_thread.is_alive()
        warmed = _op_counter("scanner_tpu_op_precompile_seconds")
        assert "Histogram" in warmed
        assert warmed["Histogram"] >= 0.0
        for ki in te.kernels.values():
            assert ki._warm_state in ("done", "idle")
    finally:
        te.close()


def test_precompile_skips_geometry_changed_inputs(sc, monkeypatch):
    """An op downstream of a geometry-changing kernel (Resize) must not
    warm at the SOURCE geometry — that would compile a ladder of
    wrong-shape executables and stall the first real call behind them.
    First-hop consumers of source frames stay warmable.  (Fusion off:
    this pins the STAGED warm-up contract — fused, Resize+Histogram
    becomes one chain that legitimately warms through the geometry
    change; test_fusion.py covers that side.)"""
    from scanner_tpu.engine.evaluate import TaskEvaluator
    from scanner_tpu.graph import analysis as A
    from scanner_tpu.graph import fusion
    from scanner_tpu.util.profiler import Profiler

    monkeypatch.setenv("SCANNER_TPU_PRECOMPILE", "1")
    frame = sc.io.Input([NamedVideoStream(sc, "bk")])
    small = sc.ops.Resize(frame=frame, width=[32], height=[24])
    hist = sc.ops.Histogram(frame=small)
    outp = sc.io.Output(hist, [NamedStream(sc, "warm_skip")])
    info = A.analyze([outp])
    prev = fusion.enabled()
    fusion.set_enabled(False)
    try:
        te = TaskEvaluator(info, Profiler(), precompile=(H, W, 8))
    finally:
        fusion.set_enabled(prev)
    try:
        states = {ki.node.name: ki._warm_state
                  for ki in te.kernels.values()}
        assert states["Histogram"] == "idle"   # geometry unknown: skip
        assert states["Resize"] != "idle"      # source frames: warmable
        if te._precompile_thread is not None:
            te._precompile_thread.join(timeout=60)
    finally:
        te.close()


def test_precompile_claim_beats_warmup(sc, monkeypatch):
    """A real call racing ahead of the warm-up thread claims the kernel:
    ensure_warm() never deadlocks and the warm-up skips it."""
    from scanner_tpu.engine.evaluate import KernelInstance

    monkeypatch.setenv("SCANNER_TPU_PRECOMPILE", "1")
    frame = sc.io.Input([NamedVideoStream(sc, "bk")])
    node = sc.ops.Histogram(frame=frame).op
    from scanner_tpu.util.profiler import Profiler
    ki = KernelInstance(node, Profiler())
    ki.setup()
    try:
        ki._warm_state = "pending"
        ki.ensure_warm()                       # claims
        assert ki._warm_state == "done"
        ki.precompile([4, 8], H, W)            # must skip, not re-run
        assert ki._warm_state == "done"        # and never deadlock
    finally:
        ki.close()


# ---------------------------------------------------------------------------
# contiguous-range fast path (ColumnBatch.take_rows / take_range)
# ---------------------------------------------------------------------------

def _mk_batch(rows, with_nulls=False):
    rows = np.asarray(rows, np.int64)
    data = (np.arange(len(rows) * 3).reshape(len(rows), 3)
            + rows[:, None] * 100)
    nulls = None
    if with_nulls:
        nulls = np.zeros(len(rows), bool)
        nulls[::3] = True
    return ColumnBatch(rows, data, nulls)


def test_take_range_contiguous_is_view():
    b = _mk_batch(np.arange(10, 30))
    out = b.take_range(14, 22)
    assert np.array_equal(out.rows, np.arange(14, 22))
    assert np.array_equal(out.data, b.data[4:12])
    # direct slice, not a gather copy
    assert out.data.base is b.data or out.data.base is b.data.base


def test_take_rows_fast_path_matches_gather():
    b = _mk_batch(np.arange(10, 30), with_nulls=True)
    rows = np.arange(14, 22)
    want = b.take(b.positions(rows), rows)
    got = b.take_rows(rows)
    assert np.array_equal(got.rows, want.rows)
    assert np.array_equal(got.data, want.data)
    assert np.array_equal(got.nulls, want.nulls)


def test_take_range_gapped_rows_fall_back():
    # rows with a hole: the fast path must detect the gap and gather
    b = _mk_batch(np.asarray([0, 1, 2, 5, 6, 7]))
    with pytest.raises(KeyError):
        b.take_range(0, 6)  # rows 3,4 missing
    out = b.take_range(5, 8)
    assert np.array_equal(out.rows, np.asarray([5, 6, 7]))
    assert np.array_equal(out.data, b.data[3:])


def test_take_rows_non_contiguous_unchanged():
    b = _mk_batch(np.arange(0, 40, 2))  # even rows only
    out = b.take_rows(np.asarray([0, 4, 10]))
    assert np.array_equal(out.data, b.data[[0, 2, 5]])
