"""Row-math tests for samplers and DAG analysis.

These encode the reference's executable spec (tests/py_test.py) at the
row-derivation level, before the engine exists: the same cases are re-run
end-to-end in test_engine.py.
"""

import numpy as np
import pytest

from scanner_tpu.common import (DeviceType, FrameType, GraphException,
                                SliceList)
from scanner_tpu.graph import analysis as A
from scanner_tpu.graph import ops as O
from scanner_tpu.graph import samplers as S
from scanner_tpu.graph.streams_dsl import (IOGenerator, StreamsGenerator,
                                           TaskPartitioner)
from typing import Any

io = IOGenerator()
streams = StreamsGenerator()
partitioner = TaskPartitioner()
ops = O.OpGenerator()


class FakeStream:
    is_video = False

    def __init__(self, n):
        self.n = n


@O.register_op(name="Flow", device=DeviceType.CPU, stencil=[-1, 0])
class _Flow(O.Kernel):
    def execute(self, frame: FrameType) -> bytes:  # pragma: no cover
        return b""


@O.register_op(name="Incr", bounded_state=3)
class _Incr(O.Kernel):
    def execute(self, ignore: bytes) -> bytes:  # pragma: no cover
        return b""


@O.register_op(name="IncrU", unbounded_state=True)
class _IncrU(O.Kernel):
    def execute(self, ignore: bytes) -> bytes:  # pragma: no cover
        return b""


@O.register_op(name="Pass")
class _Pass(O.Kernel):
    def execute(self, x: bytes) -> bytes:  # pragma: no cover
        return b""


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------

def brute_downstream(sampler, num_upstream):
    """Downstream domain via upstream_rows inversion, for cross-checking."""
    n_down = sampler.num_downstream(num_upstream)
    return n_down


@pytest.mark.parametrize("stride,n", [(8, 720), (3, 10), (1, 5), (7, 7)])
def test_strided_sampler(stride, n):
    s = S.StridedSampler(stride)
    assert s.num_downstream(n) == -(-n // stride)
    down = np.arange(s.num_downstream(n))
    up = s.upstream_rows(down)
    assert (up == down * stride).all()
    d2, mapping = s.downstream_map(up)
    assert (d2 == down).all()
    assert (mapping == np.arange(len(up))).all()


def test_strided_ranges_sampler():
    s = S.StridedRangesSampler([0, 100], [11, 201], 1)
    assert s.num_downstream(720) == 11 + 101
    assert list(s.upstream_rows([0, 10, 11, 111])) == [0, 10, 100, 200]
    # inputs are always rows previously requested via upstream_rows, i.e.
    # within the ranges (the reference drops between-range rows the same way)
    down, mapping = s.downstream_map(np.array([0, 5, 100, 150]))
    assert list(down) == [0, 5, 11, 61]
    assert list(mapping) == [0, 1, 2, 3]
    # strided variant
    s = S.StridedRangesSampler([0], [300], 10)
    assert s.num_downstream(720) == 30
    assert list(s.upstream_rows([0, 1, 29])) == [0, 10, 290]
    # partial coverage sizing
    s = S.StridedRangesSampler([0, 100], [50, 200], 1)
    assert s.num_downstream(150) == 50 + 50
    assert s.num_downstream(40) == 40


def test_gather_sampler():
    s = S.GatherSampler([0, 150, 377, 500])
    assert s.num_downstream(720) == 4
    assert s.num_downstream(300) == 2
    assert list(s.upstream_rows([0, 2])) == [0, 377]
    down, mapping = s.downstream_map(np.array([0, 150, 377, 500]))
    assert list(down) == [0, 1, 2, 3]


def test_space_samplers():
    s = S.SpaceNullSampler(8)
    assert s.num_downstream(90) == 720
    assert list(s.upstream_rows([0, 7, 8, 63])) == [0, 1, 7]
    down, mapping = s.downstream_map(np.array([0, 2]))
    assert list(down[:3]) == [0, 1, 2]
    assert mapping[0] == 0 and mapping[1] == -1
    assert mapping[8] == 1 and mapping[9] == -1

    r = S.SpaceRepeatSampler(8)
    down, mapping = r.downstream_map(np.array([3]))
    assert list(down) == list(range(24, 32))
    assert (mapping == 0).all()


def test_partitioners():
    p = S.StridedPartitioner(720, 1, 50)
    assert p.total_groups() == 15
    assert list(p.group_at(0)) == list(range(50))
    assert list(p.group_at(14)) == list(range(700, 720))
    assert p.offset_at_group(2) == 100

    p = S.StridedRangePartitioner(720, [0, 5, 15], [15, 25, 35], 1)
    assert p.total_groups() == 3
    assert list(p.group_at(1)) == list(range(5, 25))

    p = S.GatherPartitioner(720, [[0, 5], [7]])
    assert p.rows_per_group() == [2, 1]

    with pytest.raises(GraphException):
        S.StridedRangePartitioner(720, [0], [721], 1)


# ---------------------------------------------------------------------------
# graph construction + forward sizing
# ---------------------------------------------------------------------------

def _rows_for(out_node, n_in=720, job=0):
    info = A.analyze([out_node])
    src = info.sources[0]
    return info, A.job_rows(info, job, {src.id: n_in})


def test_sample_sizing():
    frame = io.Input([FakeStream(720)])
    for build, expected in [
        (lambda f: streams.Stride(f, [{"stride": 8}]), 90),
        (lambda f: streams.Range(f, [(0, 30)]), 30),
        (lambda f: streams.StridedRange(f, [(0, 300, 10)]), 30),
        (lambda f: streams.Gather(f, [[0, 150, 377, 500]]), 4),
    ]:
        out = io.Output(build(frame), [FakeStream(0)])
        info, jr = _rows_for(out)
        assert jr.output_rows == expected, build


def test_space_sizing():
    frame = io.Input([FakeStream(90)])
    sp = streams.Repeat(frame, [8])
    out = io.Output(sp, [FakeStream(0)])
    _, jr = _rows_for(out, 90)
    assert jr.output_rows == 720


def test_slice_unslice_sizing_and_tasks():
    frame = io.Input([FakeStream(720)])
    sl = streams.Slice(frame, [partitioner.all(50)])
    un = streams.Unslice(sl)
    out = io.Output(un, [FakeStream(0)])
    info, jr = _rows_for(out)
    assert jr.output_rows == 720
    assert jr.num_groups == 15
    assert jr.group_ends[:3] == [50, 100, 150]
    # tasks never cross group boundaries
    tasks = A.generate_tasks(jr, io_packet_size=64)
    for s, e in tasks:
        g = np.searchsorted(np.asarray(jr.group_ends), s, side="right")
        assert e <= jr.group_ends[g]
    assert sum(e - s for s, e in tasks) == 720


def test_overlapping_slice_with_per_group_args():
    frame = io.Input([FakeStream(720)])
    sl = streams.Slice(frame, [partitioner.strided_ranges(
        [(0, 15), (5, 25), (15, 35)], 1)])
    sampled = streams.Range(sl, [SliceList([
        {"start": 0, "end": 10},
        {"start": 5, "end": 15},
        {"start": 5, "end": 15},
    ])])
    un = streams.Unslice(sampled)
    out = io.Output(un, [FakeStream(0)])
    info, jr = _rows_for(out)
    assert jr.output_rows == 30
    assert jr.group_ends == [10, 20, 30]
    # task in group 1 pulls source rows from the overlapping range
    plan = A.derive_task_streams(info, jr, (10, 20))
    assert plan.slice_group == 1
    src_id = info.sources[0].id
    # group 1 covers source rows 5..25; Range start 5 end 15 within group =>
    # local rows 5..15 => global rows 10..20
    assert list(plan.source_rows[src_id]) == list(range(10, 20))


def test_multiple_outputs_row_mismatch():
    frame = io.Input([FakeStream(720)])
    s1 = streams.Range(frame, [(0, 30)])
    s2 = streams.Range(frame, [(0, 15)])
    o1 = io.Output(s1, [FakeStream(0)])
    o2 = io.Output(s2, [FakeStream(0)])
    info = A.analyze([o1, o2])
    with pytest.raises(GraphException):
        A.job_rows(info, 0, {info.sources[0].id: 720})
    # equal rows fine
    s2b = streams.Range(frame, [(30, 60)])
    o2b = io.Output(s2b, [FakeStream(0)])
    info = A.analyze([o1, o2b])
    jr = A.job_rows(info, 0, {info.sources[0].id: 720})
    assert jr.output_rows == 30


# ---------------------------------------------------------------------------
# backward derivation
# ---------------------------------------------------------------------------

def test_stencil_derivation_cases():
    # case: sample [0,1) then stencil [-1,0] -- needs source row 0 only
    frame = io.Input([FakeStream(720)])
    sampled = streams.Range(frame, [(0, 1)])
    flow = ops.Flow(frame=sampled)
    out = io.Output(flow, [FakeStream(0)])
    info, jr = _rows_for(out)
    assert jr.output_rows == 1
    plan = A.derive_task_streams(info, jr, (0, 1))
    src = info.sources[0].id
    assert list(plan.source_rows[src]) == [0]

    # case: stencil [0,1] over sampled stream of length 2
    frame = io.Input([FakeStream(720)])
    sampled = streams.Range(frame, [(0, 2)])
    flow = ops.Flow(frame=sampled, stencil=[0, 1])
    out = io.Output(flow, [FakeStream(0)])
    info, jr = _rows_for(out)
    plan = A.derive_task_streams(info, jr, (0, 2))
    flow_stream = plan.streams[flow.op.id]
    # row 1's stencil neighbor 2 is out of the sampled domain -> clamped
    assert list(flow_stream.valid_input_rows) == [0, 1]
    assert list(flow_stream.valid_output_rows) == [0, 1]

    # case: stencil then sample: flow over full stream, then range [0,1)
    frame = io.Input([FakeStream(720)])
    flow = ops.Flow(frame=frame)  # stencil [-1, 0]
    sampled = streams.Range(flow, [(0, 1)])
    out = io.Output(sampled, [FakeStream(0)])
    info, jr = _rows_for(out)
    assert jr.output_rows == 1
    plan = A.derive_task_streams(info, jr, (0, 1))
    assert list(plan.source_rows[info.sources[0].id]) == [0]

    # stencil reaching backward mid-stream pulls the extra source row
    frame = io.Input([FakeStream(720)])
    flow = ops.Flow(frame=frame)
    sampled = streams.Range(flow, [(100, 101)])
    out = io.Output(sampled, [FakeStream(0)])
    info, jr = _rows_for(out)
    plan = A.derive_task_streams(info, jr, (0, 1))
    assert list(plan.source_rows[info.sources[0].id]) == [99, 100]


def test_bounded_state_warmup_derivation():
    # reference test_bounded_state: gather [0,10,25,26,27], warmup 3
    frame = io.Input([FakeStream(720)])
    incr = ops.Incr(ignore=frame)
    sampled = streams.Gather(incr, [[0, 10, 25, 26, 27]])
    out = io.Output(sampled, [FakeStream(0)])
    info, jr = _rows_for(out)
    assert jr.output_rows == 5
    plan = A.derive_task_streams(info, jr, (0, 5))
    ts = plan.streams[incr.op.id]
    assert list(ts.compute_rows) == [0, 7, 8, 9, 10, 22, 23, 24, 25, 26, 27]
    assert list(ts.valid_output_rows) == [0, 10, 25, 26, 27]


def test_unbounded_state_derivation():
    frame = io.Input([FakeStream(720)])
    incr = ops.IncrU(ignore=frame)
    sampled = streams.Gather(incr, [[5, 9]])
    out = io.Output(sampled, [FakeStream(0)])
    info, jr = _rows_for(out)
    plan = A.derive_task_streams(info, jr, (0, 2))
    ts = plan.streams[incr.op.id]
    assert list(ts.compute_rows) == list(range(10))


def test_task_crossing_group_boundary_rejected():
    frame = io.Input([FakeStream(720)])
    sl = streams.Slice(frame, [partitioner.all(50)])
    un = streams.Unslice(sl)
    out = io.Output(un, [FakeStream(0)])
    info, jr = _rows_for(out)
    with pytest.raises(GraphException):
        A.derive_task_streams(info, jr, (40, 60))


def test_validation_errors():
    # sliced stream must be unsliced before output
    frame = io.Input([FakeStream(720)])
    sl = streams.Slice(frame, [partitioner.all(50)])
    out = io.Output(sl, [FakeStream(0)])
    with pytest.raises(GraphException):
        A.analyze([out])

    # job count mismatch
    frame = io.Input([FakeStream(720), FakeStream(300)])
    s1 = streams.Range(frame, [(0, 10)])  # one arg for two streams
    out = io.Output(s1, [FakeStream(0), FakeStream(0)])
    with pytest.raises(GraphException):
        A.analyze([out])


def test_per_job_args():
    frame = io.Input([FakeStream(720), FakeStream(300)])
    s1 = streams.Range(frame, [(0, 30), (10, 25)])
    out = io.Output(s1, [FakeStream(0), FakeStream(0)])
    info = A.analyze([out])
    assert info.num_jobs == 2
    jr0 = A.job_rows(info, 0, {info.sources[0].id: 720})
    jr1 = A.job_rows(info, 1, {info.sources[0].id: 300})
    assert jr0.output_rows == 30
    assert jr1.output_rows == 15
    plan = A.derive_task_streams(info, jr1, (0, 15))
    assert list(plan.source_rows[info.sources[0].id]) == list(range(10, 25))
