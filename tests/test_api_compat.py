"""The scannerpy-compatibility surface (docs/migration.md): every name a
reference user ports to must exist with the documented shape.  This is
the migration guide's executable contract."""


import scanner_tpu as sp


def test_top_level_names():
    for name in ("Client", "Table", "NamedStream", "NamedVideoStream",
                 "PerfParams", "CacheMode", "DeviceType", "FrameType",
                 "Kernel", "KernelConfig", "register_op",
                 "register_python_op", "NullElement", "BoundaryCondition",
                 "ScannerException", "GraphException", "JobException"):
        assert hasattr(sp, name), f"missing top-level name {name}"
    # reference-style device names keep working
    assert sp.DeviceType.GPU is sp.DeviceType.TPU
    assert sp.register_python_op is sp.register_op


def test_client_surface():
    for name in ("run", "ingest_videos", "ingest_images", "new_table",
                 "table", "summarize", "load_op", "batch_load",
                 "load_frames", "get_profile", "stop"):
        assert callable(getattr(sp.Client, name)), f"Client.{name}"


def test_streams_dsl_surface():
    from scanner_tpu.graph.streams_dsl import StreamsGenerator
    for name in ("All", "Stride", "Range", "Ranges", "StridedRange",
                 "StridedRanges", "Gather", "RepeatNull", "Repeat",
                 "Slice", "Unslice"):
        assert hasattr(StreamsGenerator, name), f"streams.{name}"


def test_perf_params_surface():
    # reference arg order: manual(work_packet_size, io_packet_size)
    assert sp.PerfParams.manual(4, 16).io_packet_size == 16
    est = sp.PerfParams.estimate()
    assert getattr(est, "_estimate", False)


def test_kernel_lifecycle_surface():
    for name in ("fetch_resources", "setup_with_resources", "new_stream",
                 "reset", "execute"):
        assert hasattr(sp.Kernel, name), f"Kernel.{name}"


def test_stored_stream_surface():
    for name in ("load", "len", "committed", "delete"):
        assert hasattr(sp.NamedStream, name), f"NamedStream.{name}"
    assert hasattr(sp.NamedVideoStream, "save_mp4")


def test_model_zoo_ops_registered():
    import scanner_tpu.kernels   # noqa: F401
    import scanner_tpu.models    # noqa: F401
    from scanner_tpu.graph.ops import registry
    for op in ("Histogram", "Resize", "Blur", "OpticalFlow", "CropResize",
               "HistDiff", "Grayscale", "ImageEncode", "PoseDetect",
               "ObjectDetect", "FaceDetect", "FaceEmbedding",
               "InstanceSegment"):
        registry.get(op)  # raises if unregistered


def test_parallel_layer_surface():
    """The TPU-native parallel layer the docs promise: mesh axes,
    attention schemes, pipeline + halo helpers, multi-host wiring."""
    from scanner_tpu import parallel as par

    for name in ("make_mesh", "auto_axes", "shard_batch", "sharding",
                 "make_pipeline", "stack_stage_params",
                 "make_ring_attention", "make_ulysses_attention",
                 "reference_attention", "sharded_stencil_map",
                 "temporal_diff", "CoordinatorConfig", "host_local_array",
                 "initialize", "is_initialized", "replicate_to_global"):
        assert hasattr(par, name), f"missing parallel.{name}"
    assert par.AXIS_ORDER == ("dp", "sp", "tp")


def test_model_weight_utilities_surface():
    """Weight-path utilities the guide names: shipped weights, portable
    npz export/import, orbax checkpointing, pp layout converters."""
    from scanner_tpu.models import checkpoint as ck
    from scanner_tpu.models.pose import (pp_params_to_plain,
                                         plain_params_to_pp)

    for name in ("TrainCheckpointer", "load_params", "init_or_restore",
                 "shipped_weights", "export_params_npz",
                 "import_params_npz"):
        assert hasattr(ck, name), f"missing checkpoint.{name}"
    assert callable(pp_params_to_plain) and callable(plain_params_to_pp)
    for w in ("pose_blobnet_w8.npz", "detect_ssd_w8.npz",
              "face_ssd_w8.npz", "embed_w8.npz", "seg_w8.npz"):
        assert ck.shipped_weights(w), f"shipped weight file missing: {w}"
