"""Work-packet streaming (PerfParams.stream_work_packets).

A task's io packet never materializes whole: chunk plans drive an
incremental decoder session (DecoderAutomata.stream_frames — repeated
non-reset decode_run_pts calls) through a bounded loader->evaluator
queue, with kernel state carried across chunk boundaries.  Reference
analog: the element cache + feeder threads
(evaluate_worker.h:207-218, decoder_automata.cpp).
"""

import os
import struct
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, FrameType, Kernel, NamedStream,
                         NamedVideoStream, PerfParams, register_op)
from scanner_tpu import video as scv
from scanner_tpu.storage import metadata as md
from scanner_tpu.video.automata import DecoderAutomata

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("case", ["plain", "bframe", "ogop", "vfr"])
def test_stream_frames_matches_get_frames(tmp_db, tmp_path, case):
    """The incremental decode session is frame-exact vs the one-shot
    path on every stream shape (closed GOP, reordered B frames,
    open GOP, VFR) and on random gathers."""
    kw = {
        "plain": dict(num_frames=90, keyint=12),
        "bframe": dict(num_frames=90, keyint=12, bframes=2),
        "ogop": dict(num_frames=90, keyint=12, bframes=2, open_gop=True),
        "vfr": dict(num_frames=60, keyint=12, bframes=2,
                    frame_pts=np.cumsum(
                        np.random.RandomState(1).randint(1, 4, 60)
                    ).tolist()),
    }[case]
    p = str(tmp_path / f"{case}.mp4")
    scv.synthesize_video(p, width=64, height=48, **kw)
    _, failed = scv.ingest_videos(tmp_db, [(case, p)])
    assert not failed
    desc = tmp_db.table_descriptor(case)
    vd = scv.load_video_meta(tmp_db, case)
    n = kw["num_frames"]
    rng = np.random.RandomState(7)
    path = md.column_item_path(desc.id, "frame", 0)
    for rows in (list(range(n)),
                 sorted(rng.choice(n, 20, replace=False).tolist()),
                 [0, 11, 12, 13, n - 1]):
        a = DecoderAutomata(tmp_db.backend, vd, path)
        ref = a.get_frames(rows)
        a.close()
        a = DecoderAutomata(tmp_db.backend, vd, path)
        got = {}
        for rr, fr in a.stream_frames(rows, packets_per_call=7):
            for r, f in zip(rr.tolist(), fr):
                assert r not in got, "duplicate yield"
                got[r] = f
        a.close()
        assert sorted(got) == sorted(set(rows))
        for i, r in enumerate(rows):
            assert (got[r] == ref[i]).all(), (case, r)


@register_op(name="StreamTracker", unbounded_state=True)
class StreamTracker(Kernel):
    total_rows = [0]

    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.x = 0

    def execute(self, ignore: FrameType) -> bytes:
        StreamTracker.total_rows[0] += 1
        v = self.x
        self.x += 1
        return struct.pack("=q", v)


@pytest.mark.parametrize("affinity,expected_rows", [(False, 96), (True, 64)])
def test_chunked_state_carry(tmp_path, affinity, expected_rows):
    """Chunk plans inside one task carry unbounded state chunk-to-chunk.

    Without affinity: chunk 0 of each task recomputes the task prefix
    (rows 0..start), later chunks carry — 2 tasks x 4 chunks over 64
    rows consume 32 + 64 = 96 rows (vs 2*(8+16+24+32)=160 + prefixes
    unchunked).  With affinity the inter-task chain stacks on the
    intra-task carry: every row consumed exactly once (64) — state
    flows across every chunk AND task boundary."""
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=64, width=64, height=48, fps=24,
                         keyint=8)
    sc = Client(db_path=str(tmp_path / "db"), num_load_workers=1)
    try:
        sc.ingest_videos([("t", vid)])
        StreamTracker.total_rows[0] = 0
        frame = sc.io.Input([NamedVideoStream(sc, "t")])
        out = NamedStream(sc, "o")
        jid = sc.run(sc.io.Output(sc.ops.StreamTracker(ignore=frame),
                                  [out]),
                     PerfParams.manual(
                         8, 32, stateful_task_affinity=affinity),
                     cache_mode=CacheMode.Overwrite, show_progress=False)
        vals = [struct.unpack("=q", b)[0] for b in out.load()]
        assert vals == list(range(64))
        assert StreamTracker.total_rows[0] == expected_rows, \
            StreamTracker.total_rows[0]
        stats = sc.get_profile(jid).statistics()
        assert stats["_counters"]["stream_chunks"] == 8
    finally:
        sc.stop()


def test_chunking_off_when_disabled(tmp_path):
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=32, width=64, height=48, fps=24)
    sc = Client(db_path=str(tmp_path / "db"))
    try:
        sc.ingest_videos([("t", vid)])
        import scanner_tpu.kernels  # noqa: F401
        frame = sc.io.Input([NamedVideoStream(sc, "t")])
        out = NamedStream(sc, "o")
        jid = sc.run(sc.io.Output(sc.ops.Histogram(frame=frame), [out]),
                     PerfParams.manual(8, 32, stream_work_packets=False),
                     cache_mode=CacheMode.Overwrite, show_progress=False)
        stats = sc.get_profile(jid).statistics()
        assert "stream_chunks" not in stats.get("_counters", {})
        assert len(list(out.load())) == 32
    finally:
        sc.stop()


_RSS_CHILD = r"""
import os, resource, sys, tempfile
import numpy as np
stream = sys.argv[1] == "1"
os.environ["SCANNER_TPU_STREAM_PACKETS"] = "1" if stream else "0"
root = tempfile.mkdtemp(prefix="rss_")
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels
from scanner_tpu import video as scv
vid = os.path.join(root, "big.mp4")
# 1600x1200 RGB = 5.8 MB/frame; 96-frame io packet = ~553 MB materialized
scv.synthesize_video(vid, num_frames=96, width=1600, height=1200, fps=24,
                     keyint=8)
sc = Client(db_path=os.path.join(root, "db"), num_load_workers=1)
sc.ingest_videos([("big", vid)])
frame = sc.io.Input([NamedVideoStream(sc, "big")])
out = NamedStream(sc, "h")
sc.run(sc.io.Output(sc.ops.Histogram(frame=frame), [out]),
       PerfParams.manual(8, 96), cache_mode=CacheMode.Overwrite,
       show_progress=False)
assert len(list(out.load())) == 96
sc.stop()
print("MAXRSS", resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
"""


@pytest.mark.slow
def test_streaming_bounds_peak_memory():
    """The 4K-memory claim, measured: one 96-frame 1600x1200 io packet
    (~553 MB decoded) run with 8-row chunks must peak far below the
    whole-packet run (reference element-cache bound)."""
    from scanner_tpu.util.jaxenv import cpu_only_env

    def rss(stream: bool) -> int:
        # n_devices=1: the child must NOT inherit the suite's 8-virtual-
        # device XLA_FLAGS — per-device buffers would dwarf (and equalize)
        # the decode-path memory this test measures
        r = subprocess.run(
            [sys.executable, "-c", _RSS_CHILD, "1" if stream else "0"],
            capture_output=True, text=True, timeout=420,
            env=cpu_only_env(n_devices=1), cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        for ln in r.stdout.splitlines():
            if ln.startswith("MAXRSS"):
                return int(ln.split()[1])
        raise AssertionError(f"no MAXRSS in output: {r.stdout[-500:]}")

    peak_stream = rss(True)
    peak_whole = rss(False)
    # the whole-packet run holds the 553 MB batch (plus copies); the
    # streamed run holds a few ~50 MB chunks.  Require a decisive margin
    # rather than an exact model of the allocator.
    assert peak_stream < peak_whole - 250_000, \
        f"stream {peak_stream} kB vs whole {peak_whole} kB"
