"""Child process for the multi-host mesh test: joins a 2-process JAX
runtime (4 virtual CPU devices each), builds a GLOBAL 8-device mesh, and
runs one sharded train step whose collectives cross the process boundary.

Usage: python multihost_child.py <coordinator_port> <process_id> [n_procs]
"""

import sys

from scanner_tpu.parallel.distributed import CoordinatorConfig, initialize


def spawn_multihost(n_processes: int = 2, devices_per_process: int = 4,
                    timeout: float = 600.0):
    """Launch n child processes running this script against one fresh
    coordinator and collect their stdout.  Kills the whole set if any
    child fails or times out (no orphans blocked on a dead coordinator).
    Returns the list of child stdouts."""
    import os
    import socket
    import subprocess

    from scanner_tpu.util.jaxenv import cpu_only_env

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = os.path.abspath(__file__)
    env = cpu_only_env(n_devices=devices_per_process)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, child, str(port), str(pid), str(n_processes)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n_processes)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(f"multihost child failed:\n{out}\n{err}")
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def main() -> None:
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    n_procs = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    initialize(CoordinatorConfig(
        address=f"localhost:{port}", num_processes=n_procs, process_id=pid),
        init_timeout=60)

    import jax
    assert jax.process_count() == n_procs, jax.process_count()

    from scanner_tpu.models import make_sharded_train_step
    from scanner_tpu.parallel import auto_axes, make_mesh

    # e.g. dp=2 x sp=2 x tp=2 over 8 devices spanning both processes
    mesh = make_mesh(auto_axes(jax.device_count()))
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(4, 8, 32, 32, 3), width=8)
    params, opt_state, loss = step(params, opt_state, clip, target)
    loss = float(loss)
    assert loss == loss and abs(loss) != float("inf"), loss
    print(f"MULTIHOST_LOSS {loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
