"""Child process for the multi-host mesh tests: joins an n-process JAX
runtime (k virtual CPU devices each), builds a GLOBAL mesh, and runs one
sharded train step whose collectives cross the process boundaries.

Usage: python multihost_child.py <coordinator_port> <process_id> [n_procs]
                                 [mode]
mode: "train" (default), "crash" — exits(1) right after joining the
runtime, simulating a host dying mid-job (the surviving ranks must
fail or be killable, never complete wrongly) — or "gather": every rank
stages its UNEVEN shard of 7 rows (4 + 3 under the ceil-chunk layout)
through all_gather_rows and prints the digest of the full gathered
block, proving the zero-padded staging slices back to exact logical
rows on every process.

Every mode prints MULTIHOST_JOINED once the runtime rendezvous
completes, so a launcher can kill a rank deterministically AFTER the
group formed — the SIGKILL-mid-collective harness the gang scheduler's
e2e drill reuses (spawn_multihost(sigkill_rank=...)).
"""

import sys

from scanner_tpu.parallel.distributed import CoordinatorConfig, initialize


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def spawn_multihost(n_processes: int = 2, devices_per_process: int = 4,
                    timeout: float = 600.0, crash_rank=None, port=None,
                    sigkill_rank=None, mode: str = "train"):
    """Launch n child processes running this script against one fresh
    coordinator and collect their stdout.  `timeout` bounds the WHOLE
    launch (shared deadline across children).  Kills the set on any
    failure or timeout (no orphans blocked on a dead coordinator).
    Returns the list of child stdouts.

    crash_rank: that child runs mode="crash" — it must join the runtime
    (prints MULTIHOST_JOINED) and then exit(1).  spawn_multihost verifies
    that really happened, verifies no surviving rank completes
    successfully, and raises RuntimeError — the deterministic
    rank-death-fails-the-group proof.
    sigkill_rank: that child runs NORMALLY but is SIGKILLed the moment
    it prints MULTIHOST_JOINED — host death after the group formed,
    with the victim's peers inside (or entering) the collective.  Same
    verification and RuntimeError contract as crash_rank.
    port: explicit coordinator port (reuse across launches to prove a
    fresh group can bind where a failed one died)."""
    import os
    import subprocess
    import time

    from scanner_tpu.util.jaxenv import cpu_only_env

    if port is None:
        port = free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = os.path.abspath(__file__)
    env = cpu_only_env(n_devices=devices_per_process)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    deadline = time.time() + timeout
    procs = [subprocess.Popen(
        [sys.executable, child, str(port), str(pid), str(n_processes),
         "crash" if pid == crash_rank else mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(n_processes)]

    def remaining() -> float:
        return max(0.1, deadline - time.time())

    def _assert_group_failed(victim_rank: int) -> None:
        """Survivors must never complete successfully; hanging in the
        collective (until our kill) and erroring out are both
        acceptable failure shapes."""
        grace = time.time() + 15
        for i, p in enumerate(procs):
            if i == victim_rank:
                continue
            try:
                o, _e = p.communicate(
                    timeout=max(0.1, grace - time.time()))
                if p.returncode == 0:
                    raise AssertionError(
                        f"rank {i} completed despite peer death:\n{o}")
            except subprocess.TimeoutExpired:
                pass  # blocked in the collective: expected
        raise RuntimeError(
            "rank death confirmed: group did not complete")

    outs = []
    try:
        if sigkill_rank is not None:
            import threading

            pk = procs[sigkill_rank]
            joined_ev = threading.Event()

            def _watch_join() -> None:
                # a reader thread: the blocking readline must not be
                # able to defeat the whole-launch timeout when the
                # victim wedges silently before printing anything
                for line in pk.stdout:
                    if "MULTIHOST_JOINED" in line:
                        joined_ev.set()
                        return

            wt = threading.Thread(target=_watch_join, daemon=True)
            wt.start()
            if not joined_ev.wait(timeout=remaining()):
                raise AssertionError(
                    "sigkill victim never joined the runtime")
            pk.kill()  # SIGKILL: host death after the group formed
            pk.wait()
            _assert_group_failed(sigkill_rank)
        if crash_rank is not None:
            pc = procs[crash_rank]
            out, err = pc.communicate(timeout=remaining())
            if pc.returncode != 1 or "MULTIHOST_JOINED" not in out:
                raise AssertionError(
                    f"crash child did not die after joining: "
                    f"rc={pc.returncode}\n{out}\n{err}")
            _assert_group_failed(crash_rank)
        for p in procs:
            out, err = p.communicate(timeout=remaining())
            if p.returncode != 0:
                raise RuntimeError(f"multihost child failed:\n{out}\n{err}")
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def main() -> None:
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    n_procs = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    mode = sys.argv[4] if len(sys.argv) > 4 else "train"
    initialize(CoordinatorConfig(
        address=f"localhost:{port}", num_processes=n_procs, process_id=pid),
        init_timeout=60)

    import jax
    assert jax.process_count() == n_procs, jax.process_count()
    # every mode announces the rendezvous: launchers key deterministic
    # rank kills off this line (spawn_multihost sigkill_rank)
    print("MULTIHOST_JOINED", flush=True)
    if mode == "crash":
        # simulate this host dying mid-job, after the group is formed
        sys.exit(1)

    if mode == "gather":
        # uneven all-gather proof: 7 rows over the host axis stage as
        # 4 + 3 (ceil-chunk, zero-padded to an even global) and gather
        # back to the exact logical rows on EVERY rank
        import zlib

        import numpy as np

        from scanner_tpu.parallel.distributed import (all_gather_rows,
                                                      shard_rows)
        from scanner_tpu.parallel.mesh import host_mesh

        n_rows = 7
        mesh = host_mesh(n_procs)
        lo, hi = shard_rows(n_rows, pid, n_procs)
        full = (np.arange(n_rows * 3, dtype=np.float32)
                .reshape(n_rows, 3) * 1.5)
        out = all_gather_rows(mesh, "hosts", full[lo:hi],
                              global_rows=n_rows)
        digest = zlib.crc32(np.ascontiguousarray(out).tobytes())
        status = "ok" if np.array_equal(out, full) else "BAD"
        print(f"MULTIHOST_GATHER {digest} {status}", flush=True)
        return

    from scanner_tpu.models import make_sharded_train_step
    from scanner_tpu.parallel import auto_axes, make_mesh

    # e.g. dp=2 x sp=2 x tp=2 over 8 devices spanning both processes
    mesh = make_mesh(auto_axes(jax.device_count()))
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(4, 8, 32, 32, 3), width=8)
    params, opt_state, loss = step(params, opt_state, clip, target)
    loss = float(loss)
    assert loss == loss and abs(loss) != float("inf"), loss
    print(f"MULTIHOST_LOSS {loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
