"""Distributed master/worker tests.

Capability parity with the reference's fault suite (py_test.py:788-1121):
no-workers timeout, fault tolerance via SIGKILL + elastic rejoin, job
blacklisting, task timeout.
"""

import os
import signal
import subprocess
import sys
import time
from typing import Any

import cloudpickle
import numpy as np
import pytest

import scanner_tpu
from scanner_tpu import (CacheMode, Client, FrameType, JobException, Kernel,
                         NamedStream, NamedVideoStream, PerfParams,
                         ScannerException, register_op)
import scanner_tpu.kernels  # noqa: F401
from scanner_tpu import video as scv
from scanner_tpu.engine.service import (Master, Worker, start_worker)

# test kernels must travel to worker subprocesses inside the job spec
cloudpickle.register_pickle_by_value(sys.modules[__name__])

N_FRAMES = 48


@register_op(name="DistSleep")
class DistSleep(Kernel):
    def execute(self, ignore: FrameType) -> bytes:
        time.sleep(0.2)
        return b"z"


@register_op(name="DistFail")
class DistFail(Kernel):
    def execute(self, frame: FrameType) -> bytes:
        raise RuntimeError("deliberate failure")


@register_op(name="DistHist")
class DistHist(Kernel):
    # thread names that ran execute(), keyed for the pipelining tests:
    # threaded pipelines run kernels on "eval-<i>" threads, the serial
    # debug mode runs them inline on the worker's job thread
    executed_on = []

    def execute(self, frame: FrameType) -> Any:
        import threading
        DistHist.executed_on.append(threading.current_thread().name)
        return np.asarray(frame).mean(axis=(0, 1))


@pytest.fixture()
def cluster(tmp_path):
    """Master + 2 in-process workers on ephemeral ports."""
    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=12)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("test1", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0)
    addr = f"localhost:{master.port}"
    workers = [Worker(addr, db_path=db_path) for _ in range(2)]
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, workers, db_path, addr
    sc.stop()
    for w in workers:
        w.stop()
    master.stop()


@pytest.mark.parametrize("no_pipelining", [False, True])
def test_distributed_histogram(cluster, monkeypatch, no_pipelining):
    """The bulk path with the threaded pipeline AND the serial debug
    mode (SCANNER_TPU_NO_PIPELINING): identical results and master
    bookkeeping, and the kernel-recorded thread names prove which
    execution path actually ran."""
    sc, master, workers, _dbp, _addr = cluster
    if no_pipelining:
        monkeypatch.setenv("SCANNER_TPU_NO_PIPELINING", "1")
    else:
        monkeypatch.delenv("SCANNER_TPU_NO_PIPELINING", raising=False)
    DistHist.executed_on.clear()
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    h = sc.ops.DistHist(frame=frame)
    out = NamedStream(sc, "dist_hist")
    sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == N_FRAMES
    assert rows[0].shape == (3,)
    # content correct (mean R of frame 0 is 0)
    assert rows[0][0] < 3
    assert DistHist.executed_on, "kernel never ran in-process"
    on_eval_threads = [t.startswith("eval-") for t in DistHist.executed_on]
    if no_pipelining:
        assert not any(on_eval_threads), DistHist.executed_on
    else:
        assert all(on_eval_threads), DistHist.executed_on


def test_distributed_multiworker_progress(cluster):
    sc, master, workers, _dbp, addr = cluster
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    s = sc.ops.DistSleep(ignore=frame)
    out = NamedStream(sc, "dist_sleep")
    t0 = time.time()
    sc.run(sc.io.Output(s, [out]), PerfParams.manual(4, 8),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    dt = time.time() - t0
    assert out.len() == N_FRAMES
    # 48 frames x 0.2s = 9.6s serial; 2 workers must beat ~85% of serial
    assert dt < 9.6 * 0.85, f"no parallel speedup: {dt:.1f}s"


def test_shutdown_cluster_rpc(cluster):
    """Client.shutdown_cluster: the master fans Shutdown out to every
    registered worker, then releases its own wait_for_shutdown — the
    remote counterpart of SIGTERM drain for blocking deployments
    (scanner-check SC306/SC307 keep the method wired and classified)."""
    sc, master, workers, _dbp, _addr = cluster
    assert sc.job_status().get("num_workers") == 2
    assert sc.shutdown_cluster() == 2
    assert master._shutdown.is_set()
    for w in workers:
        assert w._shutdown.wait(timeout=2.0)


def test_pipelined_worker_speedup(tmp_path):
    """One worker with P=3 pipeline instances must run eval-bound work
    ~P x faster than serial (the reference's per-node pipeline instance
    scaling, worker.cpp:1467-1724) — and the PerfParams knob must be
    honored by the cluster worker."""
    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    n = 24
    scv.synthesize_video(vid, num_frames=n, width=64, height=48, fps=24,
                         keyint=12)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("test1", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0)
    addr = f"localhost:{master.port}"
    worker = Worker(addr, db_path=db_path)
    sc = Client(db_path=db_path, master=addr)
    try:
        def run_with(instances: int, name: str) -> float:
            frame = sc.io.Input([NamedVideoStream(sc, "test1")])
            s = sc.ops.DistSleep(ignore=frame)
            out = NamedStream(sc, name)
            t0 = time.time()
            # pipeline_instances_per_node travels in the job's PerfParams
            sc.run(sc.io.Output(s, [out]),
                   PerfParams.manual(
                       4, 8, pipeline_instances_per_node=instances),
                   cache_mode=CacheMode.Overwrite, show_progress=False)
            assert out.len() == n
            return time.time() - t0

        dt1 = run_with(1, "pipe_sleep_serial")   # 3 tasks x 1.6s serial
        dt3 = run_with(3, "pipe_sleep_par")      # 3 tasks concurrent
        # fixed client/poll overhead cancels in the comparison; demand the
        # parallel run recovers most of the 3.2s of serialized sleep
        assert dt1 - dt3 > 2.0, \
            f"no pipeline-instance speedup on one worker: " \
            f"P=1 {dt1:.1f}s vs P=3 {dt3:.1f}s"
    finally:
        sc.stop()
        worker.stop()
        master.stop()


def test_engine_logging_transitions(cluster, caplog):
    """Key engine state transitions are logged through the scanner_tpu
    logging tree (reference glog/VLOG coverage, util/glog.h): worker
    registration, bulk admission, task assignment/completion, bulk
    finish, and failure paths."""
    import logging
    sc, master, workers, _dbp, _addr = cluster
    with caplog.at_level(logging.DEBUG, logger="scanner_tpu"):
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        h = sc.ops.DistHist(frame=frame)
        out = NamedStream(sc, "log_out")
        sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    text = caplog.text
    assert "admitted" in text            # bulk admission
    assert "assigned to worker" in text  # task assignment
    assert "finished by worker" in text  # task completion
    assert "bulk" in text and "finished:" in text  # bulk completion
    # failure path logging
    with caplog.at_level(logging.DEBUG, logger="scanner_tpu"):
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        f = sc.ops.DistFail(frame=frame)
        out2 = NamedStream(sc, "log_fail_out")
        with pytest.raises(ScannerException):
            sc.run(sc.io.Output(f, [out2]), PerfParams.manual(8, 8),
                   cache_mode=CacheMode.Overwrite, show_progress=False)
    assert "failed on worker" in caplog.text
    assert "blacklisted" in caplog.text


def test_scanner_tpu_log_env(tmp_path):
    """SCANNER_TPU_LOG attaches a stderr handler at the given level."""
    import subprocess
    import sys

    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env["SCANNER_TPU_LOG"] = "debug"
    r = subprocess.run(
        [sys.executable, "-c",
         "from scanner_tpu.util.log import get_logger; "
         "get_logger('master').debug('probe-message-xyz')"],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "probe-message-xyz" in r.stderr
    assert "scanner_tpu.master" in r.stderr


def test_checkpoint_frequency_periodic_megafile(cluster, monkeypatch):
    """checkpoint_frequency=1 makes the master write the metadata megafile
    as tasks complete, not only at bulk end (reference master.cpp:1100-1113
    checkpoint every N jobs)."""
    sc, master, workers, _dbp, _addr = cluster
    calls = []
    orig = master.db.write_megafile
    monkeypatch.setattr(master.db, "write_megafile",
                        lambda: (calls.append(1), orig())[1])
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    h = sc.ops.DistHist(frame=frame)
    out = NamedStream(sc, "ckpt_out")
    sc.run(sc.io.Output(h, [out]),
           PerfParams.manual(4, 8, checkpoint_frequency=1),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    n_tasks = (N_FRAMES + 7) // 8
    # one write per completed task plus the bulk-end write
    assert len(calls) >= n_tasks, f"megafile written {len(calls)} times"


def test_long_task_survives_stale_scan(cluster):
    """A single task running longer than WORKER_STALE_AFTER must not be
    revoked — the background heartbeat keeps the busy worker alive."""
    sc, master, workers, _dbp, _addr = cluster
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 40)])
    s = sc.ops.DistSleep(ignore=sampled)
    out = NamedStream(sc, "long_out")
    # 40 frames x 0.2s = 8s in ONE task (> 6s stale threshold)
    sc.run(sc.io.Output(s, [out]), PerfParams.manual(40, 40),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert out.len() == 40 and out.committed()


def test_job_status_reports_progress_and_fps(cluster):
    """GetJobStatus carries the live-status fields /statusz shares:
    per-job tasks done/total, per-stage fps, ETA, worker count."""
    sc, master, workers, _dbp, addr = cluster
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    h = sc.ops.DistHist(frame=frame)
    out = NamedStream(sc, "status_out")
    sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    st = master._rpc_job_status({})
    assert st["finished"] is True
    assert st["tasks_done"] == st["total_tasks"]
    n_tasks = (N_FRAMES + 7) // 8
    assert st["tasks_done"] == n_tasks
    # per-stage fps derived from the master-observed transitions: every
    # row passed every stage, so all three are positive and roughly equal
    assert set(st["stage_fps"]) == {"load", "evaluate", "save"}
    assert all(v > 0 for v in st["stage_fps"].values()), st["stage_fps"]
    # ETA only exists while the bulk is unfinished
    assert st["eta_seconds"] is None
    assert st["elapsed_seconds"] > 0
    per_job = st["per_job"]
    assert len(per_job) == 1
    (job,) = per_job.values()
    assert job["tasks_done"] == job["tasks_total"] == n_tasks
    assert job["blacklisted"] is False
    # blacklisted jobs are flagged per job
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    f = sc.ops.DistFail(frame=frame)
    out2 = NamedStream(sc, "status_fail_out")
    with pytest.raises(ScannerException):
        sc.run(sc.io.Output(f, [out2]), PerfParams.manual(8, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    st2 = master._rpc_job_status({})
    assert any(j["blacklisted"] for j in st2["per_job"].values())
    assert st2["failed_jobs"]


def test_stage_rows_not_double_counted_on_retry():
    """A retried attempt's second StartedWork/EvalDone must not inflate
    the per-stage row counts GetJobStatus reports — on a flaky cluster
    the load fps would otherwise read (retries+1)x the save fps."""
    from scanner_tpu.engine.service import _BulkJob

    bulk = _BulkJob(bulk_id=0, spec_blob=b"", task_timeout=0.0)
    bulk.task_rows[(0, 0)] = 8
    bulk.count_stage("load", (0, 0))
    bulk.count_stage("load", (0, 0))      # re-issued attempt
    bulk.count_stage("evaluate", (0, 0))
    bulk.count_stage("evaluate", (0, 0))
    assert bulk.stage_rows == {"load": 8, "evaluate": 8, "save": 0}


def test_ops_registry_resolves_canonical_class_identity():
    """The PR 10 flake root cause, pinned: a cloudpickle
    register_pickle_by_value round-trip of the job spec can hand the
    evaluator a *class copy* of a registered op — kernels then record
    class-level state (DistHist.executed_on) on the copy while readers
    hold the original.  The registry resolves a same-named,
    same-qualname factory back to the registered original; genuinely
    different classes (spawned workers, name reuse) pass through."""
    import dataclasses

    from scanner_tpu.graph import ops as O

    spec = DistHist._op_spec
    assert O.registry.canonical_factory(spec) is DistHist

    # simulate the by-value copy cloudpickle mints when its class
    # tracker misses: same module + qualname, different object
    copy_cls = type(DistHist.__name__, (Kernel,), {
        "__module__": DistHist.__module__,
        "__qualname__": DistHist.__qualname__,
        "executed_on": [],
        "execute": DistHist.execute,
    })
    assert copy_cls is not DistHist
    spec_copy = dataclasses.replace(spec, kernel_factory=copy_cls)
    assert O.registry.canonical_factory(spec_copy) is DistHist

    # a same-named class from a DIFFERENT module is NOT the same op:
    # the spec's own factory stands (spawned-worker semantics)
    alien = type(DistHist.__name__, (Kernel,), {
        "__module__": "somewhere.else",
        "__qualname__": DistHist.__qualname__,
    })
    spec_alien = dataclasses.replace(spec, kernel_factory=alien)
    assert O.registry.canonical_factory(spec_alien) is alien

    # and the evaluator path instantiates the canonical class: a
    # KernelInstance built from a copy-carrying node runs the ORIGINAL
    # (whose executed_on the flaky test reads), not the copy
    from scanner_tpu.engine.evaluate import KernelInstance
    from scanner_tpu.util.profiler import Profiler

    inp = O.OpNode(O.INPUT_OP, {})
    node = O.OpNode("DistHist", {"frame": inp.outputs[0]})
    node.spec = spec_copy
    ki = KernelInstance(node, Profiler(node="test"))
    assert type(ki.kernel) is DistHist
    ki.close()


def test_op_spec_roundtrip_resolves_registry_and_preserves_state():
    """The actual flake mechanism, pinned: unpickling a by-value class
    in the SAME process re-applies its pickled __dict__ onto the
    deduped original, REBINDING class attributes to dump-time copies —
    DistHist.executed_on appends made after the dump vanished when a
    late-joining worker loaded the job spec.  OpSpec.__reduce__ now
    nests the class blob and the restore resolves through the
    registry, so an in-process round trip touches no class state and
    returns THE registered spec object."""
    from scanner_tpu.graph import ops as O

    spec = DistHist._op_spec
    blob = cloudpickle.dumps(spec)
    before = DistHist.executed_on
    DistHist.executed_on.append("sentinel-after-dump")
    try:
        spec2 = cloudpickle.loads(blob)
        # canonical identity: the registered spec itself comes back
        assert spec2 is O.registry.get("DistHist")
        assert spec2.kernel_factory is DistHist
        # and the round trip did NOT clobber class state: the list is
        # the same object and the post-dump append survived
        assert DistHist.executed_on is before
        assert "sentinel-after-dump" in DistHist.executed_on
    finally:
        DistHist.executed_on.clear()
    # a process WITHOUT the registration still reconstructs a working
    # spec from the nested class blob (the spawned-worker path)
    orig = O.registry._ops.pop("DistHist")
    try:
        spec3 = cloudpickle.loads(blob)
        assert spec3 is not orig
        assert spec3.kernel_factory is not None
        assert spec3.kernel_factory.__qualname__ == "DistHist"
        assert spec3.name == "DistHist"
    finally:
        O.registry._ops["DistHist"] = orig


def test_cluster_profiles(cluster):
    sc, master, workers, _dbp, _addr = cluster
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    h = sc.ops.DistHist(frame=frame)
    out = NamedStream(sc, "prof_dist")
    job_id = sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
                    cache_mode=CacheMode.Overwrite, show_progress=False)
    stats = sc.get_profile(job_id).statistics()
    assert any(k.startswith("task") or k.startswith("evaluate")
               for k in stats), stats


def test_no_workers(tmp_path):
    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=12, width=64, height=48, fps=24)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("test1", vid)])
    master = Master(db_path=db_path, no_workers_timeout=2.0)
    sc = Client(db_path=db_path, master=f"localhost:{master.port}")
    try:
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        h = sc.ops.DistHist(frame=frame)
        out = NamedStream(sc, "nw_out")
        with pytest.raises(ScannerException):
            sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
                   cache_mode=CacheMode.Overwrite, show_progress=False)
    finally:
        sc.stop()
        master.stop()


def test_job_blacklist(cluster):
    sc, master, workers, _dbp, _addr = cluster
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    f = sc.ops.DistFail(frame=frame)
    out = NamedStream(sc, "bl_out")
    with pytest.raises(ScannerException):
        sc.run(sc.io.Output(f, [out]), PerfParams.manual(4, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    assert not out.committed()


def test_job_timeout(cluster):
    sc, master, workers, _dbp, _addr = cluster
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 8)])
    s = sc.ops.DistSleep(ignore=sampled)
    out = NamedStream(sc, "to_out")
    with pytest.raises(ScannerException):
        sc.run(sc.io.Output(s, [out]), PerfParams.manual(8, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False,
               task_timeout=0.5)
    assert not out.committed()


def test_fault_tolerance(tmp_path):
    """SIGKILL a subprocess worker mid-job; a replacement joins; the job
    completes with correct output (reference py_test.py:922)."""
    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=24, width=64, height=48, fps=24,
                         keyint=12)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("test1", vid)])
    master = Master(db_path=db_path, no_workers_timeout=60.0)
    addr = f"localhost:{master.port}"
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    spawn = os.path.join(os.path.dirname(__file__), "spawn_worker.py")

    def spawn_worker():
        return subprocess.Popen(
            [sys.executable, spawn, addr, db_path],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    victim = spawn_worker()

    import threading
    def killer():
        time.sleep(3.0)
        victim.kill()
        victim.wait()
        time.sleep(1.0)
        spawn_worker.replacement = spawn_worker()
    kt = threading.Thread(target=killer)
    kt.start()

    sc = Client(db_path=db_path, master=addr)
    try:
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        s = sc.ops.DistSleep(ignore=frame)
        out = NamedStream(sc, "ft_out")
        sc.run(sc.io.Output(s, [out]), PerfParams.manual(2, 4),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        kt.join()
        assert out.len() == 24
        assert out.committed()
    finally:
        kt.join()
        repl = getattr(spawn_worker, "replacement", None)
        if repl is not None:
            repl.kill()
            repl.wait()
        sc.stop()
        master.stop()


def test_rpc_backoff_rides_out_server_restart():
    """A transiently-unreachable server (UNAVAILABLE) is retried with
    exponential backoff instead of failing immediately — the analog of the
    reference's GRPC_BACKOFF wrapper (scanner/util/grpc.h)."""
    import socket
    import threading

    from scanner_tpu.engine.rpc import RpcClient, RpcError, RpcServer

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    def make_server():
        srv = RpcServer("Test", {"Echo": lambda req: {"v": req["v"]}},
                        port=port)
        srv.start()
        return srv

    client = RpcClient(f"localhost:{port}", "Test", timeout=5.0,
                       retries=25, backoff_base=0.05, backoff_cap=0.3)
    try:
        # server comes up only after a delay: the first attempts get
        # UNAVAILABLE and must be retried, not surfaced
        started = {}
        def later():
            time.sleep(0.3)
            started["srv"] = make_server()
        t = threading.Thread(target=later)
        t.start()
        try:
            assert client.call("Echo", v=7)["v"] == 7
        finally:
            t.join()
            started["srv"].stop()

        # with retries disabled the same situation fails fast
        with pytest.raises(RpcError):
            client.call("Echo", v=8, retries=0)

        # restart on the same port: a fresh call reconnects and succeeds
        srv2 = make_server()
        try:
            assert client.call("Echo", v=9)["v"] == 9
        finally:
            srv2.stop()
    finally:
        client.close()


def test_rpc_try_call_returns_none_after_retries():
    from scanner_tpu.engine.rpc import RpcClient

    client = RpcClient("localhost:1", "Test", timeout=1.0, retries=2,
                       backoff_base=0.01, backoff_cap=0.02)
    try:
        t0 = time.time()
        assert client.try_call("Echo", v=1) is None
        assert time.time() - t0 < 5.0
    finally:
        client.close()


@register_op(name="RowProbe")
class RowProbe(Kernel):
    """Recovers the synthetic frame's row index (blue-square x position,
    unique mod 56 for <56 rows) and appends it to a shared log file —
    lets tests assert exactly which rows were (re)executed."""

    def __init__(self, config, log_path: str = ""):
        super().__init__(config)
        self._log = log_path

    def execute(self, frame: FrameType) -> bytes:
        import numpy as np
        from scanner_tpu.video.ingest import frame_pattern_id
        f = np.asarray(frame)
        sq = max(4, f.shape[0] // 8)
        span = max(1, f.shape[1] - sq)
        x = int(np.asarray(f[:sq, :, 2].mean(axis=0) > 128).argmax())
        # R channel gives i%14 exactly; the blue-square x (i*5 % span,
        # candidates 14 apart -> 70%span px apart) disambiguates which
        pid = frame_pattern_id(f)
        row = min(range(pid, 56, 14),
                  key=lambda c: abs((c * 5) % span - x))
        time.sleep(0.05)
        with open(self._log, "a") as fh:
            fh.write(f"{row}\n")
        return str(row).encode()


def test_master_restart_recovers_bulk(tmp_path):
    """SIGKILL the MASTER mid-bulk; a restarted master on the same db_path
    resumes the job from its checkpoint: the bulk completes, and tasks in
    the persisted done-set are NOT re-executed (reference
    recover_and_init_database master.cpp:1311 + checkpoint 1100-1113)."""
    import socket
    import threading

    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    log = str(tmp_path / "rows.log")
    n = 24
    scv.synthesize_video(vid, num_frames=n, width=64, height=48, fps=24,
                         keyint=4)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("test1", vid)])
    seed.stop()

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    addr = f"localhost:{port}"
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    spawn = os.path.join(os.path.dirname(__file__), "spawn_master.py")

    def spawn_master():
        return subprocess.Popen(
            [sys.executable, spawn, db_path, str(port)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    from scanner_tpu.engine import journal as _journal
    from scanner_tpu.storage.backend import PosixStorage
    prog_backend = PosixStorage(db_path)

    def _persisted_done():
        # the progress snapshot lives at the generation-scoped sealed
        # path now (engine/journal.py); the helper resolves + verifies
        prog = _journal.load_bulk_progress(prog_backend)
        if not prog or "done_runs" not in prog:
            return set()
        return Master._decode_task_set(prog["done_runs"])

    m1 = spawn_master()
    worker = None
    m2 = None
    state = {}

    def killer():
        # wait until >=3 tasks are in the persisted done-set, then SIGKILL
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if len(_persisted_done()) >= 3:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        m1.kill()
        m1.wait()
        state["done_at_kill"] = _persisted_done()
        state["rows_at_kill"] = open(log).read().splitlines()
        time.sleep(1.0)
        state["m2"] = spawn_master()

    try:
        sc = Client(db_path=db_path, master=addr)
        worker = Worker(addr, db_path=db_path)
        kt = threading.Thread(target=killer)
        kt.start()
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        probe = sc.ops.RowProbe(frame=frame, log_path=log)
        out = NamedStream(sc, "restart_out")
        # work=1/io=2 -> 12 tasks; checkpoint_frequency=1 persists the
        # done-set after every task
        sc.run(sc.io.Output(probe, [out]),
               PerfParams.manual(1, 2, checkpoint_frequency=1),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        kt.join()
        m2 = state.get("m2")
        assert state["done_at_kill"], "master was never killed mid-bulk"

        # output correct and committed
        rows = list(out.load())
        assert [int(r) for r in rows] == list(range(n))
        assert out.committed()

        # rows of tasks that were in the persisted done-set at kill time
        # must appear exactly once in the probe log (not re-executed)
        counts = {}
        for line in open(log).read().splitlines():
            counts[int(line)] = counts.get(int(line), 0) + 1
        for (_j, t) in state["done_at_kill"]:
            for row in (2 * t, 2 * t + 1):
                assert counts.get(row, 0) == 1, \
                    f"row {row} of finished task {t} ran " \
                    f"{counts.get(row, 0)} times"
        # and every row ran at least once
        assert all(counts.get(r, 0) >= 1 for r in range(n))
    finally:
        if worker is not None:
            worker.stop()
        sc.stop()
        for p in (m1, state.get("m2")):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def test_scheduler_dispatch_throughput(tmp_path):
    """50k-task dispatch against the in-process master scheduler: the
    deque queue + O(1) held-count must sustain >=1k NextWork dispatches
    per second through the full assign -> start -> evaldone -> finish
    cycle (the reference shards tasks for cluster scale,
    master.cpp:1558-1607; this proves the same ceiling here)."""
    from scanner_tpu.engine.service import Master, _BulkJob

    master = Master(db_path=str(tmp_path / "db"), no_workers_timeout=60.0)
    try:
        n_jobs, tasks_per_job = 1000, 50
        bulk = _BulkJob(bulk_id=0, spec_blob=b"", task_timeout=0.0)
        for j in range(n_jobs):
            tasks = {(j, t) for t in range(tasks_per_job)}
            bulk.job_tasks[j] = tasks
            bulk.job_sink_names[j] = []
            bulk.job_custom_sinks[j] = []
            bulk.job_output_rows[j] = 0
            bulk.queue[j] = __import__("collections").deque(
                sorted(t for _j, t in tasks))
            bulk.job_rr.append(j)
            bulk.total_tasks += len(tasks)
        with master._lock:
            master._bulk = bulk
            master._history[0] = bulk
        n_workers = 8
        wids = [master._rpc_register_worker({"address": f"w{i}"})
                ["worker_id"] for i in range(n_workers)]

        total = n_jobs * tasks_per_job
        t0 = time.time()
        dispatched = 0
        while dispatched < total:
            for wid in wids:
                r = master._rpc_next_work(
                    {"worker_id": wid, "bulk_id": 0, "window": 8})
                if r["status"] != "task":
                    continue
                base = {"worker_id": wid, "bulk_id": 0,
                        "job_idx": r["job_idx"], "task_idx": r["task_idx"],
                        "attempt": r["attempt"]}
                assert master._rpc_started_work(dict(base))["ok"]
                assert master._rpc_eval_done(dict(base))["ok"]
                assert master._rpc_finished_work(dict(base))["ok"]
                dispatched += 1
        dt = time.time() - t0
        rate = total / dt
        assert bulk.finished
        assert len(bulk.done) == total
        assert not bulk.held, bulk.held
        # 4 RPC handler calls per task; demand >=1k full task cycles/s
        assert rate >= 1000, f"dispatch rate {rate:.0f} tasks/s"
        print(f"scheduler dispatch: {rate:.0f} task cycles/s "
              f"({total} tasks, {dt:.2f}s)")
    finally:
        master.stop()


def test_scheduler_concurrent_dispatch_stress(tmp_path):
    """Many worker threads hammer the master's RPC handlers concurrently
    (the real server dispatches from a thread pool): every task completes
    exactly once, counters balance, no deadlock."""
    import threading

    from scanner_tpu.engine.service import Master, _BulkJob

    master = Master(db_path=str(tmp_path / "db"), no_workers_timeout=60.0)
    try:
        n_jobs, tasks_per_job = 200, 25
        bulk = _BulkJob(bulk_id=0, spec_blob=b"", task_timeout=0.0)
        for j in range(n_jobs):
            tasks = {(j, t) for t in range(tasks_per_job)}
            bulk.job_tasks[j] = tasks
            bulk.job_sink_names[j] = []
            bulk.job_custom_sinks[j] = []
            bulk.job_output_rows[j] = 0
            bulk.queue[j] = __import__("collections").deque(
                sorted(t for _j, t in tasks))
            bulk.job_rr.append(j)
            bulk.total_tasks += len(tasks)
        with master._lock:
            master._bulk = bulk
            master._history[0] = bulk

        completed = []
        lock = threading.Lock()

        def worker_thread():
            wid = master._rpc_register_worker({"address": "x"})["worker_id"]
            done_here = 0
            while True:
                r = master._rpc_next_work(
                    {"worker_id": wid, "bulk_id": 0, "window": 4})
                if r["status"] in ("done", "none"):
                    # "none" = bulk finished (a sibling completed the
                    # last task); real workers exit via the same signal
                    break
                if r["status"] != "task":
                    time.sleep(0.0005)
                    continue
                base = {"worker_id": wid, "bulk_id": 0,
                        "job_idx": r["job_idx"], "task_idx": r["task_idx"],
                        "attempt": r["attempt"]}
                assert master._rpc_started_work(dict(base))["ok"]
                assert master._rpc_eval_done(dict(base))["ok"]
                assert master._rpc_finished_work(dict(base))["ok"]
                done_here += 1
            with lock:
                completed.append(done_here)

        threads = [threading.Thread(target=worker_thread)
                   for _ in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "dispatch deadlocked"
        assert sum(completed) == n_jobs * tasks_per_job
        assert bulk.finished
        assert len(bulk.done) == bulk.total_tasks
        assert not bulk.outstanding and not bulk.held
    finally:
        master.stop()


def test_progress_task_set_codec():
    """Run-length task-set codec round-trips arbitrary done-sets (the
    progress checkpoint stores intervals, not 10^6 tuples)."""
    import random

    rng = random.Random(3)
    for _ in range(20):
        tasks = {(rng.randrange(5), rng.randrange(50))
                 for _ in range(rng.randrange(0, 120))}
        enc = Master._encode_task_set(tasks)
        assert Master._decode_task_set(enc) == tasks
    # contiguous million-task job encodes tiny
    big = {(0, t) for t in range(100000)}
    enc = Master._encode_task_set(big)
    assert enc == {0: [0, 100000]}
    assert Master._decode_task_set({}) == set()


def test_distributed_chain_matches_oracle(cluster):
    """The cluster path (gRPC master + 2 pull workers) must preserve
    exact-row semantics on a sampler/stencil/state/slice composition —
    the same oracle discipline as tests/test_property_fuzz.py, through
    worker-side DAG re-analysis and out-of-order task completion."""
    import struct as _struct

    sc, master, workers, db_path, addr = cluster
    n0 = 40

    def pk(v):
        return _struct.pack("<q", v)

    def unpk(b):
        return _struct.unpack("<q", b)[0]

    sc.new_table("chain_src", ["output"],
                 [[pk(100 + i)] for i in range(n0)])

    # slice into [0,17) [17,40); per group: stencil sum then cumsum
    intervals = [(0, 17), (17, 40)]
    col = sc.io.Input([NamedStream(sc, "chain_src")])
    col = sc.streams.Slice(col, partitions=[
        sc.partitioner.strided_ranges(intervals, 1)])
    col = sc.ops._DistStencilSum(x=col)
    col = sc.ops._DistCumSum(x=col)
    # (unslice may only feed the output op — reference invariant, so the
    # composition ends here)
    col = sc.streams.Unslice(col)
    out = NamedStream(sc, "chain_out")
    sc.run(sc.io.Output(col, [out]), PerfParams.manual(2, 4),
           cache_mode=CacheMode.Overwrite, show_progress=False)

    vals = list(range(100, 100 + n0))

    def o_sten(g):
        n = len(g)
        return [g[max(0, i - 1)] + g[i] + g[min(n - 1, i + 1)]
                for i in range(n)]

    def o_cum(g):
        acc, out_ = 0, []
        for v in g:
            acc += v
            out_.append(acc)
        return out_

    expect = []
    for a, b in intervals:
        expect.extend(o_cum(o_sten(vals[a:b])))
    got = [unpk(r) for r in out.load()]
    assert got == expect


@register_op(name="_DistStencilSum", stencil=[-1, 0, 1])
class _DistStencilSum(Kernel):
    def execute(self, x: Any) -> bytes:
        import struct as _s
        return _s.pack("<q", sum(_s.unpack("<q", b)[0] for b in x))


@register_op(name="_DistCumSum", unbounded_state=True)
class _DistCumSum(Kernel):
    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.acc = 0

    def execute(self, x: bytes) -> bytes:
        import struct as _s
        self.acc += _s.unpack("<q", x)[0]
        return _s.pack("<q", self.acc)


def test_distributed_model_op(cluster):
    """A model-zoo kernel (InstanceSegment, shipped trained weights)
    through the CLUSTER path: the cloudpickled graph must carry the
    flax kernel, workers must restore weights and pack device results,
    and the packed rows must unpack on the client side."""

    import scanner_tpu.models  # registers InstanceSegment
    from scanner_tpu.models import unpack_instances
    from scanner_tpu.models.segmentation import MASK_SIZE, TOP_K

    sc, master, workers, _dbp, _addr = cluster
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    ranged = sc.streams.Range(frame, [(0, 4)])
    inst = sc.ops.InstanceSegment(frame=ranged, width=8)
    out = NamedStream(sc, "dist_inst")
    sc.run(sc.io.Output(inst, [out]), PerfParams.manual(2, 4),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == 4
    a = np.asarray(rows[0])
    assert a.shape == (TOP_K, 6 + MASK_SIZE * MASK_SIZE)
    r = unpack_instances(rows[0])
    assert r["masks"].dtype == bool
