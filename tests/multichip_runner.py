"""Child process for tests/test_multichip.py: run the equivalence suite
on a virtual multi-device host and dump output hashes + metrics.

Spawned with cpu_only_env(n_devices=N) + SCANNER_TPU_KERNEL_DEVICES=all
so the CPU backend exposes N virtual chips and the engine's device
staging / evaluator-affinity paths engage exactly as they do on a real
multi-chip worker.  Usage:

    python multichip_runner.py <video_path> <out_json>

Env knobs the parent sets: XLA_FLAGS (virtual device count),
SCANNER_TPU_KERNEL_DEVICES=all, JAX_PLATFORMS=cpu.
"""

import hashlib
import json
import os
import sys
import tempfile

import numpy as np


def _hash_rows(rows) -> list:
    """Stable per-row digests: arrays hash shape+dtype+bytes, NullElement
    hashes to 'null', plain values repr — bit-exactness across runs is
    exactly digest equality."""
    from scanner_tpu import NullElement
    out = []
    for e in rows:
        if isinstance(e, NullElement):
            out.append("null")
        elif isinstance(e, np.ndarray) or hasattr(e, "shape"):
            a = np.ascontiguousarray(np.asarray(e))
            h = hashlib.sha256()
            h.update(str(a.shape).encode())
            h.update(str(a.dtype).encode())
            h.update(a.tobytes())
            out.append(h.hexdigest())
        else:
            out.append(repr(e))
    return out


def main() -> int:
    video, out_path = sys.argv[1], sys.argv[2]
    from scanner_tpu import (CacheMode, Client, DeviceType, FrameType,
                             Kernel, NamedStream, NamedVideoStream,
                             PerfParams, register_op)
    import scanner_tpu.kernels  # noqa: F401  (registers Histogram)
    from scanner_tpu.util.metrics import labeled_samples, registry
    from typing import Any, Sequence
    import jax

    @register_op(device=DeviceType.TPU, stencil=[-1, 0], batch=8)
    class McStencil(Kernel):
        """Stencil device kernel (2-frame window sum) — numpy-bodied so
        it is bit-exact however many chips run it."""

        def execute(self, frame: Sequence[Sequence[FrameType]]
                    ) -> Sequence[Any]:
            a = np.asarray(frame, np.int64)
            return a.reshape(len(a), -1).sum(axis=1)

    @register_op(device=DeviceType.TPU, batch=16, unbounded_state=True)
    class McTracker(Kernel):
        """Unbounded-state chain kernel: running pixel-sum accumulator.
        Under stateful_task_affinity its tasks serialize onto ONE
        instance and therefore one chip — the invariant this suite
        pins."""

        def __init__(self, config):
            super().__init__(config)
            self._acc = 0

        def reset(self):
            self._acc = 0

        def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
            f = np.asarray(frame, np.int64).reshape(len(frame), -1)
            out = []
            for i in range(len(f)):
                self._acc += int(f[i].sum()) % 100003
                out.append(self._acc)
            return out

    root = tempfile.mkdtemp(prefix="mc_")
    sc = Client(db_path=os.path.join(root, "db"))
    sc.ingest_videos([("mc", video)])

    def snap_series(name):
        return labeled_samples(registry().snapshot(), name)

    def run(name, build, affinity=True, wp=8, io=16):
        os.environ["SCANNER_TPU_DEVICE_AFFINITY"] = "1" if affinity else "0"
        before_rc = snap_series("scanner_tpu_op_recompiles_total")
        before_dev = snap_series("scanner_tpu_device_tasks_total")
        frame = sc.io.Input([NamedVideoStream(sc, "mc")])
        col, perf_kw = build(frame)
        out = NamedStream(sc, name)
        sc.run(sc.io.Output(col, [out]), PerfParams.manual(wp, io, **perf_kw),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        after_rc = snap_series("scanner_tpu_op_recompiles_total")
        after_dev = snap_series("scanner_tpu_device_tasks_total")
        return {
            "rows": _hash_rows(list(out.load())),
            "recompiles_delta": {
                k: after_rc.get(k, 0) - before_rc.get(k, 0)
                for k in after_rc},
            "device_tasks_delta": {
                k: after_dev.get(k, 0) - before_dev.get(k, 0)
                for k in after_dev},
        }

    results = {
        "n_devices": len(jax.local_devices()),
        "runs": {
            # stateless jitted stdlib op (the flagship Histogram)
            "hist": run("hist", lambda f: (sc.ops.Histogram(frame=f), {})),
            # stencil windows across chunk/task boundaries
            "stencil": run(
                "stencil", lambda f: (sc.ops.McStencil(frame=f), {})),
            # stateful chain: serializes onto one instance/chip
            "chain": run(
                "chain",
                lambda f: (sc.ops.McTracker(frame=f),
                           {"stateful_task_affinity": True})),
            # null-interleaved geometry through the bucketed call
            "nulls": run(
                "nulls",
                lambda f: (sc.ops.Histogram(
                    frame=sc.streams.RepeatNull(
                        sc.streams.Range(f, [(0, 12)]), [3])), {})),
            # the A/B lever: affinity off must restore default-chip
            # dispatch (every task on the "default" label), same results
            "hist_no_affinity": run(
                "hist_na",
                lambda f: (sc.ops.Histogram(frame=f), {}),
                affinity=False),
        },
    }
    sc.stop()
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print("MULTICHIP_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
