"""Horizontally sharded control plane: consistent-hash ring, durable
versioned shard map, map-epoch fencing, worker multiplexing
(docs/robustness.md §Sharded control plane; engine/shardmap.py).

Layers:
  * ring units — stable (non-salted) hashing, balance across shards,
    and the load-movement property: removing a dead shard's points
    moves ONLY the keys that shard owned;
  * durable-map units — CAS merge-retry registration (concurrent
    registrants all survive), epoch pruning, MapHolder adoption;
  * in-process master units — the map-epoch fence NACKing mutations
    routed with a stale map (and passing current/legacy ones);
  * in-process multiplexing — one worker linked to three shard
    masters drains bulks admitted on DIFFERENT shards;
  * the spawned 3-shard failover e2e (slow) — SIGKILL the bulk-owning
    shard mid-load, respawn it, zero journaled re-execution, bit-exact
    output, surviving shards untouched.
"""

import os
import struct
import subprocess
import sys
import threading
import time

import cloudpickle
import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         PerfParams, register_op)
from scanner_tpu.engine import shardmap
from scanner_tpu.engine.service import (MASTER_SERVICE, ClusterClient,
                                        Master, Worker)
from scanner_tpu.storage.backend import MemoryStorage, PosixStorage
from scanner_tpu.util import faults
from scanner_tpu.util import metrics as _mx

# test kernels travel to worker subprocesses inside the job spec
cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.chaos

N_ROWS = 24


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="ShardDouble")
class ShardDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return _pk(2 * struct.unpack("<q", x)[0])


EXPECT = [_pk(2 * (100 + i)) for i in range(N_ROWS)]


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    for s in entry.get("samples", []):
        if s["labels"] == labels:
            return s["value"]
    return 0.0


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def _three_shards(monkeypatch):
    """Arm the process-global shard count the Worker/Client side keys
    multiplexing off.  The env var is set too so a Client constructed
    inside the test does not clobber it back to the config default."""
    monkeypatch.setenv("SCANNER_TPU_CONTROL_SHARDS", "3")
    shardmap.set_num_shards(3)
    yield
    shardmap.set_num_shards(1)


# ---------------------------------------------------------------------------
# ring units
# ---------------------------------------------------------------------------

def test_stable_hash_is_process_stable():
    """The ring digest must agree across processes: md5-derived, never
    Python's per-process-salted hash()."""
    import hashlib
    for key in ("job-token-1", "s01/bulk/7", ""):
        want = int.from_bytes(
            hashlib.md5(key.encode()).digest()[:8], "big")
        assert shardmap.stable_hash(key) == want
    # and deterministic across calls, obviously
    assert shardmap.stable_hash("x") == shardmap.stable_hash("x")


def test_ring_balance_within_tolerance():
    smap = shardmap.ShardMap(epoch=1, shards={0: "a", 1: "b", 2: "c"})
    counts = {0: 0, 1: 0, 2: 0}
    n = 3000
    for i in range(n):
        counts[smap.shard_for(f"token-{i}")] += 1
    # VNODES=64 points/shard: every shard within [15%, 55%] of keys —
    # loose enough to never flake, tight enough to catch a broken ring
    for sid, c in counts.items():
        assert 0.15 * n < c < 0.55 * n, (sid, counts)


def test_shard_death_moves_only_dead_shards_keys():
    """THE consistent-hash property the failover design leans on:
    dropping shard 1's ring points re-routes shard 1's keys and
    nobody else's — surviving shards keep every bulk they own."""
    full = shardmap.ShardMap(epoch=1,
                             shards={0: "a", 1: "b", 2: "c"},
                             num_shards=3)
    survivor = shardmap.ShardMap(epoch=2,
                                 shards={0: "a", 2: "c"},
                                 num_shards=3)
    moved = kept = orphaned = 0
    for i in range(2000):
        key = f"token-{i}"
        before, after = full.shard_for(key), survivor.shard_for(key)
        if before == 1:
            orphaned += 1
            assert after in (0, 2)
        else:
            kept += 1
            assert after == before, \
                f"{key} moved {before}->{after} though shard " \
                f"{before} survived"
        moved += before != after
    assert orphaned > 0 and kept > 0
    assert moved == orphaned  # exactly the dead shard's keys moved


def test_shard_map_roundtrip_and_empty_routing():
    smap = shardmap.ShardMap(epoch=7, shards={0: "h0:1", 2: "h2:3"},
                             num_shards=3)
    back = shardmap.ShardMap.from_dict(smap.to_dict())
    assert back.epoch == 7 and back.num_shards == 3
    assert back.shards == {0: "h0:1", 2: "h2:3"}
    assert back.shard_ids() == [0, 2]
    assert back.address_of(2) == "h2:3"
    assert back.address_of(1) is None
    # an empty map (unsharded db) routes everything to the legacy
    # master, shard 0
    assert shardmap.ShardMap().shard_for("anything") == 0


# ---------------------------------------------------------------------------
# durable-map units
# ---------------------------------------------------------------------------

def test_register_shard_merges_and_bumps_epoch():
    s = MemoryStorage()
    assert shardmap.load(s) is None
    m1 = shardmap.register_shard(s, 0, "h0:1", num_shards=3)
    m2 = shardmap.register_shard(s, 1, "h1:1", num_shards=3)
    m3 = shardmap.register_shard(s, 2, "h2:1", num_shards=3)
    assert (m1.epoch, m2.epoch, m3.epoch) == (1, 2, 3)
    cur = shardmap.load(s)
    assert cur.epoch == 3
    assert cur.shards == {0: "h0:1", 1: "h1:1", 2: "h2:1"}
    # a respawned shard re-registering a NEW address is an epoch bump
    # that keeps every peer's entry (the failover re-publish)
    m4 = shardmap.register_shard(s, 1, "h1:9", num_shards=3)
    assert m4.epoch == 4
    assert shardmap.load(s).shards == \
        {0: "h0:1", 1: "h1:9", 2: "h2:1"}


def test_register_shard_concurrent_racers_all_survive():
    """The CAS merge-retry loop: N shards registering at once all end
    up in the final map (losers re-load and re-merge)."""
    s = MemoryStorage()
    barrier = threading.Barrier(4)

    def racer(sid):
        barrier.wait()
        shardmap.register_shard(s, sid, f"h{sid}:1", num_shards=4)

    threads = [threading.Thread(target=racer, args=(sid,))
               for sid in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    cur = shardmap.load(s)
    assert cur.shards == {sid: f"h{sid}:1" for sid in range(4)}
    assert cur.epoch >= 4  # every registration took its own epoch


def test_old_epochs_pruned():
    from scanner_tpu.storage import metadata as smd
    s = MemoryStorage()
    for _ in range(shardmap.KEEP_EPOCHS + 4):
        shardmap.register_shard(s, 0, "h0:1", num_shards=1)
    left = s.list_prefix(smd.shardmap_prefix())
    assert len(left) <= shardmap.KEEP_EPOCHS
    # the newest epoch is among the survivors
    assert any(f"e{shardmap.KEEP_EPOCHS + 4:08d}" in p for p in left)


def test_map_holder_adopts_strictly_newer():
    h = shardmap.MapHolder()
    assert h.get() is None and h.epoch() == 0
    assert h.observe(shardmap.ShardMap(epoch=3, shards={0: "a"}))
    assert h.epoch() == 3
    assert not h.observe(shardmap.ShardMap(epoch=3, shards={0: "b"}))
    assert not h.observe(shardmap.ShardMap(epoch=2, shards={0: "b"}))
    assert h.get().shards == {0: "a"}  # stale observe did not regress
    assert not h.observe(None)
    assert h.observe(shardmap.ShardMap(epoch=4, shards={0: "b"}))
    assert h.get().shards == {0: "b"}


# ---------------------------------------------------------------------------
# in-process master units: the map-epoch fence
# ---------------------------------------------------------------------------

def _seed_db(tmp_path, table="sh_src"):
    db_path = str(tmp_path / "db")
    sc = Client(db_path=db_path)
    sc.new_table(table, ["output"],
                 [[_pk(100 + i)] for i in range(N_ROWS)])
    return sc, db_path


def _spec_blob(sc, out_name, src="sh_src", **perf_kw):
    col = sc.io.Input([NamedStream(sc, src)])
    col = sc.ops.ShardDouble(x=col)
    out = NamedStream(sc, out_name)
    node = sc.io.Output(col, [out])
    return cloudpickle.dumps({
        "outputs": [node],
        "perf": PerfParams.manual(2, 2, **perf_kw),
        "cache_mode": CacheMode.Overwrite.value})


def test_map_epoch_fence_nacks_stale_map(tmp_path, _three_shards):
    """A mutation stamped with an older map epoch than the serving
    master's is NACKed with stale_map (the caller must refresh and
    re-route); the current epoch and unstamped legacy requests pass."""
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0,
               shard_id=0, num_shards=3)
    try:
        # a peer shard failed over: its re-publish bumped the epoch
        # and this master adopted the newer map
        m._adopt_shard_map(shardmap.ShardMap(
            epoch=m._map_epoch + 5,
            shards={0: f"localhost:{m.port}", 1: "h1:1", 2: "h2:1"},
            num_shards=3))
        newer = m._map_epoch
        base = _counter("scanner_tpu_shard_stale_map_rejections_total")
        wrapped = m._fenced(m._rpc_new_job)
        spec = _spec_blob(sc, "sh_fence_out")

        stale = wrapped({"spec": spec, "token": "tok-stale",
                         "map_epoch": newer - 1})
        assert stale.get("stale_map") and "error" in stale
        assert stale["map_epoch"] == newer  # the fence tells the
        assert "bulk_id" not in stale       # caller what to catch up to
        assert _counter(
            "scanner_tpu_shard_stale_map_rejections_total") == base + 1

        # the CURRENT epoch passes, and live replies are stamped with
        # the epoch so callers can latch it
        ok = wrapped({"spec": spec, "token": "tok-live",
                      "map_epoch": newer})
        assert "bulk_id" in ok and not ok.get("stale_map")
        assert ok["map_epoch"] == newer
        # an unstamped request (legacy / single-shard caller) passes
        dup = wrapped({"spec": spec, "token": "tok-live"})
        assert dup == {"bulk_id": ok["bulk_id"], "dedup": True,
                       "generation": m.generation, "map_epoch": newer}
    finally:
        m.stop()
        sc.stop()


def test_get_shard_map_served_and_refreshed(tmp_path, _three_shards):
    """Every shard serves the full versioned map; a peer's later
    registration is visible through any one shard (the startup-race
    inline refresh)."""
    sc, db_path = _seed_db(tmp_path)
    m0 = Master(db_path=db_path, no_workers_timeout=60.0,
                shard_id=0, num_shards=3)
    try:
        r = m0._rpc_get_shard_map({})
        assert r["shard_id"] == 0 and r["num_shards"] == 3
        assert "0" in r["shards"]
        # peers register AFTER shard 0 adopted its own publish
        backend = PosixStorage(db_path)
        shardmap.register_shard(backend, 1, "h1:1", num_shards=3)
        shardmap.register_shard(backend, 2, "h2:1", num_shards=3)
        r2 = m0._rpc_get_shard_map({})
        assert set(r2["shards"]) == {"0", "1", "2"}
        assert r2["epoch"] > r["epoch"]
    finally:
        m0.stop()
        sc.stop()


# ---------------------------------------------------------------------------
# in-process multiplexing: one worker, three shard masters
# ---------------------------------------------------------------------------

def test_worker_multiplexes_and_drains_all_owning_shards(
        tmp_path, _three_shards):
    """One worker linked to three shard masters drains bulks admitted
    on two DIFFERENT shards: heartbeats reach every shard (slim on
    non-active ones), the pull plumbing rebinds to whichever shard has
    work, and both outputs commit bit-exact."""
    sc, db_path = _seed_db(tmp_path)
    masters = [Master(db_path=db_path, no_workers_timeout=120.0,
                      shard_id=k, num_shards=3) for k in range(3)]
    worker = None
    try:
        worker = Worker(f"localhost:{masters[0].port}", db_path=db_path)
        deadline = time.time() + 30
        while time.time() < deadline and len(worker._links) < 3:
            time.sleep(0.1)
        assert sorted(worker._links) == [0, 1, 2], \
            "worker never linked every shard"

        # admit one bulk on shard 1, then (after it drains) one on
        # shard 2 — bypassing the client's hash routing so the shard
        # choice is deterministic.  Sequential admission: table-id
        # allocation is single-writer, the multiplexing under test is
        # the worker REBINDING its pull plumbing between owning shards.
        done = {}
        for sid, out_name, token in ((1, "sh_mux_out1", "mux-1"),
                                     (2, "sh_mux_out2", "mux-2")):
            r = masters[sid]._rpc_new_job(
                {"spec": _spec_blob(sc, out_name), "token": token})
            assert "bulk_id" in r, r
            deadline = time.time() + 120
            while time.time() < deadline:
                st = masters[sid]._rpc_job_status(
                    {"bulk_id": r["bulk_id"]})
                if st.get("finished"):
                    done[sid] = True
                    break
                time.sleep(0.25)
        assert done == {1: True, 2: True}, f"bulks not drained: {done}"
        # the worker's active link followed the work to shard 2
        assert worker._active_shard == 2
        # a fresh client: the seed client's cached metadata predates
        # the master-side output-table creation
        sc2 = Client(db_path=db_path)
        try:
            assert [bytes(r) for r in
                    NamedStream(sc2, "sh_mux_out1").load()] == EXPECT
            assert [bytes(r) for r in
                    NamedStream(sc2, "sh_mux_out2").load()] == EXPECT
        finally:
            sc2.stop()
        # the worker registered with (and beat) every shard it pulled
        # from — non-active shards got slim beats, which is the
        # coalescing the Heartbeat counter tracks
        for sid in (1, 2):
            with masters[sid]._lock:
                assert masters[sid]._workers, \
                    f"shard {sid} never saw the worker"
    finally:
        if worker is not None:
            worker.stop()
        for m in masters:
            m.stop()
        sc.stop()


# ---------------------------------------------------------------------------
# the spawned 3-shard failover e2e (slow)
# ---------------------------------------------------------------------------

def _spawn_env(extra=None):
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep \
        + env.get("PYTHONPATH", "")
    env.pop("SCANNER_TPU_FAULTS", None)
    env.pop("SCANNER_TPU_MASTER_GENERATION", None)
    env["SCANNER_TPU_CONTROL_SHARDS"] = "3"
    env.update(extra or {})
    return env


@pytest.mark.slow
def test_three_shard_failover_spawned(tmp_path, _three_shards):
    """The sharded headline, in miniature: three spawned shard
    masters, one in-process worker, a bulk under load with
    checkpoint_frequency=0, and the bulk-owning shard SIGKILL-crashed
    mid-FinishedWork (only the owner handles FinishedWork, so exactly
    it dies).  Its respawn CAS-claims the next generation in the SHARD
    namespace, replays the journal, and finishes the bulk: bit-exact
    output, failover counted, zero journaled re-execution, zero
    strikes, surviving shards never restarted."""
    import socket

    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("sh_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    seed.stop()

    ports = []
    for _ in range(3):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            ports.append(s.getsockname()[1])
    spawn = os.path.join(os.path.dirname(__file__), "spawn_master.py")

    def spawn_shard(sid, extra=None):
        return subprocess.Popen(
            [sys.executable, spawn, db_path, str(ports[sid]),
             str(sid), "3"],
            env=_spawn_env(extra),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    # the crash plan arms in every shard process, but only the shard
    # that owns the bulk ever handles FinishedWork — exactly it dies
    fault = {"SCANNER_TPU_FAULTS":
             "rpc.server.handle:crash:match=FinishedWork:n=4"}
    procs = {sid: spawn_shard(sid, extra=fault) for sid in range(3)}
    state = {}
    stop = threading.Event()

    def watcher():
        while not stop.is_set():
            for sid, p in list(procs.items()):
                rc = p.poll()
                if rc is not None and sid not in state:
                    state[sid] = rc
                    if rc == faults.CRASH_EXIT_CODE:
                        time.sleep(0.5)
                        procs[sid] = spawn_shard(sid)  # no fault plan
            time.sleep(0.1)

    wt = threading.Thread(target=watcher, daemon=True)
    wt.start()

    from scanner_tpu.engine.rpc import wait_for_server
    for sid in range(3):
        wait_for_server(f"localhost:{ports[sid]}", MASTER_SERVICE,
                        timeout=60.0)
    addr0 = f"localhost:{ports[0]}"

    sc = None
    worker = None
    try:
        sc = Client(db_path=db_path, master=addr0)
        worker = Worker(addr0, db_path=db_path)
        col = sc.io.Input([NamedStream(sc, "sh_src")])
        col = sc.ops.ShardDouble(x=col)
        out = NamedStream(sc, "sh_failover_out")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.manual(2, 2, checkpoint_frequency=0),
               cache_mode=CacheMode.Overwrite, show_progress=False)

        assert [bytes(r) for r in out.load()] == EXPECT
        assert out.committed()
        crashed = [sid for sid, rc in state.items()
                   if rc == faults.CRASH_EXIT_CODE]
        assert len(crashed) == 1, \
            f"expected exactly one shard crash, got {state}"

        # cluster-wide evidence via the shard fan-in: the respawn
        # replayed the journal, counted a failover, re-executed zero
        # journaled tasks, struck nobody
        cc = ClusterClient(addr0, None)
        try:
            snap = cc.metrics()

            def _tot(name):
                return sum(s.get("value", 0) for s in
                           snap.get(name, {}).get("samples", []))

            assert _tot("scanner_tpu_journal_replayed_records_total") \
                > 0
            assert _tot("scanner_tpu_shard_failovers_total") >= 1
            assert _tot("scanner_tpu_shard_journal_reexec_total") == 0
            assert _tot("scanner_tpu_blacklist_strikes_total") == 0
            # worst-of health fold across every shard: no survivor
            # rolled up unhealthy
            assert cc.health()["status"] != "unhealthy"
        finally:
            cc.close()
        # the two surviving shards were never restarted
        assert all(rc == faults.CRASH_EXIT_CODE
                   for rc in state.values()), state
    finally:
        stop.set()
        wt.join(timeout=5)
        if worker is not None:
            worker.stop()
        if sc is not None:
            sc.stop()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()
