"""Stateful task affinity (PerfParams.stateful_task_affinity).

Unbounded-state ops normally force every task to recompute rows 0..end
(self-contained tasks, O(n^2/io_packet) total); affinity chains a job's
tasks so kernel state carries forward — O(n) total — with the evaluator
verifying the premise against real kernel state and falling back to the
self-contained plan on any break (reference analog: save_coordinator
packet pinning, worker.cpp:373-415).
"""

import struct
import sys

import cloudpickle
import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, FrameType, Kernel, NamedStream,
                         NamedVideoStream, PerfParams, register_op)
from scanner_tpu import video as scv

cloudpickle.register_pickle_by_value(sys.modules[__name__])

N_FRAMES = 96


@register_op(name="CountingTracker", unbounded_state=True)
class CountingTracker(Kernel):
    """Emits its running row position; counts every execute() row so
    tests can assert total work (linear vs quadratic)."""

    total_rows = [0]  # class-level: survives across instances in-process

    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.x = 0

    def execute(self, ignore: FrameType) -> bytes:
        CountingTracker.total_rows[0] += 1
        v = self.x
        self.x += 1
        return struct.pack("=q", v)


@pytest.fixture()
def sc(tmp_path):
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=8)
    # one loader so chained tasks arrive at the evaluator in plan order
    # (reordering is CORRECT — it just costs a fallback recompute — but
    # the linear-work assertion wants the deterministic path)
    c = Client(db_path=str(tmp_path / "db"), num_load_workers=1)
    c.ingest_videos([("t", vid)])
    yield c
    c.stop()


def _run_tracker(sc, name, affinity, io=8):
    frame = sc.io.Input([NamedVideoStream(sc, "t")])
    col = sc.ops.CountingTracker(ignore=frame)
    out = NamedStream(sc, name)
    sc.run(sc.io.Output(col, [out]),
           PerfParams.manual(io, io, stateful_task_affinity=affinity),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return [struct.unpack("=q", b)[0] for b in out.load()]


def test_affinity_linear_work_identical_results(sc):
    CountingTracker.total_rows[0] = 0
    base = _run_tracker(sc, "no_aff", affinity=False)
    work_quadratic = CountingTracker.total_rows[0]
    assert base == list(range(N_FRAMES))
    # self-contained tasks recompute 0..end: sum_{t=1..12} 8t = 624
    n_tasks = N_FRAMES // 8
    assert work_quadratic == 8 * n_tasks * (n_tasks + 1) // 2

    CountingTracker.total_rows[0] = 0
    aff = _run_tracker(sc, "aff", affinity=True)
    work_linear = CountingTracker.total_rows[0]
    assert aff == base
    assert work_linear == N_FRAMES, \
        f"affinity consumed {work_linear} rows, expected {N_FRAMES}"


def test_affinity_with_slices_matches_plain(sc):
    """Per-slice-group state reset still holds under affinity."""
    def run(name, affinity):
        frame = sc.io.Input([NamedVideoStream(sc, "t")])
        sliced = sc.streams.Slice(frame,
                                  partitions=[sc.partitioner.all(24)])
        col = sc.ops.CountingTracker(ignore=sliced)
        unsliced = sc.streams.Unslice(col)
        out = NamedStream(sc, name)
        sc.run(sc.io.Output(unsliced, [out]),
               PerfParams.manual(8, 8, stateful_task_affinity=affinity),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        return [struct.unpack("=q", b)[0] for b in out.load()]

    assert run("sl_no", False) == [i % 24 for i in range(N_FRAMES)]
    assert run("sl_yes", True) == [i % 24 for i in range(N_FRAMES)]


def test_carry_plan_derivation(sc):
    """Carry plans recompute only past the watermark; watermarks are
    reported for the next link of the chain."""
    from scanner_tpu.engine.executor import LocalExecutor
    from scanner_tpu.graph import analysis as A

    frame = sc.io.Input([NamedVideoStream(sc, "t")])
    col = sc.ops.CountingTracker(ignore=frame)
    outputs = [sc.io.Output(col, [NamedStream(sc, "derive_out")])]
    ex = LocalExecutor(sc._db)
    info, jobs = ex.prepare(outputs, PerfParams.manual(8, 8),
                            cache_mode=CacheMode.Overwrite)
    jr = jobs[0].jr
    nid = next(n.id for n in info.ops
               if n.spec is not None and n.spec.unbounded_state)

    plain = A.derive_task_streams(info, jr, (16, 24))
    assert plain.streams[nid].compute_rows[0] == 0
    assert plain.carry_watermarks == {(nid, 0): 23}

    carried = A.derive_task_streams(info, jr, (16, 24),
                                    carry={(nid, 0): 15})
    assert carried.streams[nid].compute_rows.tolist() == list(range(16, 24))
    assert carried.carry_watermarks == {(nid, 0): 23}
    # sources shrink with the plan: only the new rows decode
    assert carried.source_rows[info.sources[0].id].tolist() == \
        list(range(16, 24))

    # a watermark past the needed outputs cannot carry (state can't
    # re-emit consumed rows): self-contained fallback at plan time
    stale = A.derive_task_streams(info, jr, (16, 24),
                                  carry={(nid, 0): 23})
    assert stale.streams[nid].compute_rows[0] == 0


def test_carry_miss_raises_and_fallback_recovers(sc):
    """Evaluating a carry plan on a kernel whose state is elsewhere
    raises StateCarryMiss; the executor fallback re-runs self-contained
    with identical results."""
    import types

    from scanner_tpu.engine.evaluate import StateCarryMiss, TaskEvaluator
    from scanner_tpu.engine.executor import LocalExecutor, TaskItem
    from scanner_tpu.graph import analysis as A
    from scanner_tpu.util.profiler import Profiler

    frame = sc.io.Input([NamedVideoStream(sc, "t")])
    col = sc.ops.CountingTracker(ignore=frame)
    outputs = [sc.io.Output(col, [NamedStream(sc, "miss_out")])]
    ex = LocalExecutor(sc._db)
    info, jobs = ex.prepare(outputs, PerfParams.manual(8, 8),
                            cache_mode=CacheMode.Overwrite)
    job = jobs[0]
    nid = next(n.id for n in info.ops
               if n.spec is not None and n.spec.unbounded_state)

    te = TaskEvaluator(info, Profiler())
    try:
        # carry plan claiming state at row 15 — but this evaluator is
        # fresh: premise broken, must raise (silent reset would emit
        # wrong values)
        w = TaskItem(job, 2, (16, 24))
        w.plan = A.derive_task_streams(info, job.jr, (16, 24), job_idx=0,
                                       task_idx=2, carry={(nid, 0): 15})
        w.elements = ex._load_sources(info, w, types.SimpleNamespace())
        with pytest.raises(StateCarryMiss):
            te.execute_task(job.jr, w.plan, w.elements)

        # the executor-level fallback reloads + re-runs self-contained
        w.elements = ex._load_sources(info, w, types.SimpleNamespace())
        res = ex._evaluate_with_fallback(info, te, w,
                                         types.SimpleNamespace())
        sink_id = info.sinks[0].id
        vals = [struct.unpack("=q", b)[0]
                for b in res[sink_id].elements()]
        assert vals == list(range(16, 24))
    finally:
        te.close()


def test_cluster_sticky_assignment(tmp_path):
    """With affinity, the master hands every task of the job to ONE
    worker, in order; results match the single-node run."""
    import scanner_tpu.kernels  # noqa: F401
    from scanner_tpu.engine.service import Master, Worker

    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=8)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("t", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0)
    addr = f"localhost:{master.port}"
    workers = [Worker(addr, db_path=db_path) for _ in range(2)]
    sc = Client(db_path=db_path, master=addr)
    try:
        CountingTracker.total_rows[0] = 0
        frame = sc.io.Input([NamedVideoStream(sc, "t")])
        col = sc.ops.CountingTracker(ignore=frame)
        out = NamedStream(sc, "aff_dist")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.manual(8, 8, stateful_task_affinity=True),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        got = [struct.unpack("=q", b)[0] for b in out.load()]
        assert got == list(range(N_FRAMES))
        bulk = master._history[max(master._history)]
        assert bulk.sticky
        assert len(set(bulk.sticky_worker.values())) == 1
    finally:
        sc.stop()
        for w in workers:
            w.stop()
        master.stop()
