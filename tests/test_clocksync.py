"""Adversarial clock-sync suite (scanner_tpu/util/clocksync.py).

The NTP-style heartbeat exchange is only useful if its failure modes
are honest, so every test here attacks the estimator the way a real
deployment would: a fixed skew, asymmetric network delay (the one error
NTP cannot remove, only bound), jittered RTT, and a step change in the
peer clock (VM migration / ntpd slew).  The assertions are about the
CONTRACT, not the arithmetic: the error stays within the published
uncertainty, the uncertainty stays bounded by RTT/2, and an
untrustworthy estimate refuses to rebase rather than smearing spans.
"""

import random
import time

import pytest

from scanner_tpu.util import clocksync
from scanner_tpu.util import faults
from scanner_tpu.util.clocksync import OffsetEstimator


def _exchange(est, true_offset, up_s, down_s, proc_s=0.0001,
              t0=1000.0):
    """Feed one four-timestamp exchange: the worker clock reads
    `true_offset` LESS than the master clock (offset estimate should
    converge to +true_offset), with `up_s`/`down_s` one-way delays."""
    t1 = t0 + true_offset + up_s              # master stamps arrival
    t2 = t1 + proc_s                          # master stamps reply
    t3 = t2 - true_offset + down_s            # worker stamps receipt
    est.add_sample(t0, t1, t2, t3)
    return t3


def test_fixed_offset_converges():
    est = OffsetEstimator()
    t0 = 1000.0
    for _ in range(40):
        t0 = _exchange(est, 0.5, up_s=0.002, down_s=0.002, t0=t0) + 1.0
    e = est.estimate()
    assert e is not None
    assert abs(e["offset"] - 0.5) < 1e-3
    # symmetric fixed delay: uncertainty is best-RTT/2 + no spread
    assert e["uncertainty"] <= 0.005
    assert e["at"] > 1000.0


def test_asymmetric_delay_error_stays_within_uncertainty():
    # the classic NTP blind spot: 9 ms up, 1 ms down biases the offset
    # by (up-down)/2 = +4 ms.  The estimator cannot remove that error —
    # the contract is that the published uncertainty COVERS it
    # (best-RTT/2 = 5 ms >= 4 ms bias).
    est = OffsetEstimator()
    t0 = 1000.0
    for _ in range(40):
        t0 = _exchange(est, 0.1, up_s=0.009, down_s=0.001, t0=t0) + 1.0
    e = est.estimate()
    assert e is not None
    err = abs(e["offset"] - 0.1)
    assert err > 1e-4          # the bias is real...
    assert err <= e["uncertainty"] + 1e-9   # ...and the bound is honest


def test_jittered_rtt_prefers_low_rtt_samples():
    # queueing jitter up to 20 ms on each leg, floor 1 ms: best-K
    # selection should keep the estimate near truth with uncertainty
    # far below the worst-case jitter
    rng = random.Random(7)
    est = OffsetEstimator()
    t0 = 1000.0
    for _ in range(64):
        up = 0.001 + rng.random() * 0.020
        down = 0.001 + rng.random() * 0.020
        t0 = _exchange(est, -0.25, up_s=up, down_s=down, t0=t0) + 1.0
    e = est.estimate()
    assert e is not None
    assert abs(e["offset"] - (-0.25)) <= e["uncertainty"] + 1e-9
    assert e["uncertainty"] < 0.020


def test_step_change_flushes_and_reconverges():
    est = OffsetEstimator()
    t0 = 1000.0
    for _ in range(40):
        t0 = _exchange(est, 0.05, up_s=0.002, down_s=0.002, t0=t0) + 1.0
    assert abs(est.estimate()["offset"] - 0.05) < 1e-3
    # the peer clock steps by 300 ms (far beyond 4x the ~1 ms bound):
    # the window must flush, so a handful of new samples reconverge
    # instead of EWMA-dragging through 32 stale ones
    for _ in range(6):
        t0 = _exchange(est, 0.35, up_s=0.002, down_s=0.002, t0=t0) + 1.0
    e = est.estimate()
    assert abs(e["offset"] - 0.35) < 1e-3


def test_non_causal_stamps_discarded():
    est = OffsetEstimator()
    # t3 before t0 net of server time: negative RTT, clock stepped
    # mid-RPC — must not poison the window
    est.add_sample(1000.0, 1000.5, 1000.5001, 999.9)
    assert est.estimate() is None
    t0 = 1000.0
    for _ in range(10):
        t0 = _exchange(est, 0.0, up_s=0.001, down_s=0.001, t0=t0) + 1.0
    assert abs(est.estimate()["offset"]) < 1e-3


def test_should_rebase_thresholds():
    assert not clocksync.should_rebase(None)
    assert not clocksync.should_rebase({})
    assert not clocksync.should_rebase(
        {"offset": 0.1, "uncertainty": 1.0})
    assert clocksync.should_rebase(
        {"offset": 0.1, "uncertainty": 0.01})
    # per-call override tightens/loosens the gate
    assert not clocksync.should_rebase(
        {"offset": 0.1, "uncertainty": 0.01}, max_uncertainty_s=0.001)
    assert clocksync.should_rebase(
        {"offset": 0.1, "uncertainty": 1.0}, max_uncertainty_s=2.0)
    # junk uncertainty is untrustworthy, not an exception
    assert not clocksync.should_rebase(
        {"offset": 0.1, "uncertainty": "nan?"})


def test_rebase_spans_shifts_trusted_nodes_only():
    spans = [
        {"node": "workerA", "name": "task", "start": 10.0, "end": 11.0,
         "events": [{"name": "barrier.enter", "t": 10.5}]},
        {"node": "workerB", "name": "task", "start": 20.0, "end": 21.0},
        {"node": "master", "name": "job", "start": 5.0, "end": 30.0},
    ]
    offsets = {
        "workerA": {"offset": 2.0, "uncertainty": 0.001},
        # beyond REBASE_MAX_UNCERTAINTY_S: raw timestamps kept
        "workerB": {"offset": 9.0, "uncertainty": 5.0},
    }
    out = clocksync.rebase_spans(spans, offsets)
    a, b, m = out
    assert a["start"] == 12.0 and a["end"] == 13.0
    assert a["events"][0]["t"] == 12.5
    assert a["clock_rebased"] is True
    assert b["start"] == 20.0 and "clock_rebased" not in b
    assert m["start"] == 5.0 and "clock_rebased" not in m
    # inputs untouched (copies, not in-place edits)
    assert spans[0]["start"] == 10.0
    assert "clock_rebased" not in spans[0]


def test_rebase_spans_duration_invariant():
    spans = [{"node": "w", "name": "op", "start": 1.0, "end": 1.5}]
    out = clocksync.rebase_spans(
        spans, {"w": {"offset": -3.0, "uncertainty": 0.0}})
    assert out[0]["end"] - out[0]["start"] == pytest.approx(0.5)


@pytest.mark.chaos
def test_heartbeat_piggyback_live_cluster(tmp_path):
    """The real wire path: an in-process master + worker exchange
    stamps on the heartbeat; the master ends up holding a published
    per-node estimate whose offset is ~0 (same host clock)."""
    from scanner_tpu.engine.service import Master, Worker
    from scanner_tpu.util.metrics import registry

    master = Master(db_path=str(tmp_path / "db"),
                    no_workers_timeout=30.0)
    worker = None
    try:
        worker = Worker(f"localhost:{master.port}",
                        db_path=str(tmp_path / "db"))
        deadline = time.time() + 15
        est = None
        while time.time() < deadline:
            with master._lock:
                offs = dict(master._clock_offsets)
            if offs:
                est = next(iter(offs.values()))
                break
            time.sleep(0.1)
        assert est is not None, "no clock estimate reached the master"
        # same host, loopback RPC: offset within a generous 50 ms
        assert abs(est["offset"]) < 0.05
        assert est["uncertainty"] < 0.25
        snap = registry().snapshot()
        for series in clocksync.CLOCKSYNC_SERIES:
            assert snap.get(series, {}).get("samples"), series
    finally:
        if worker is not None:
            worker.stop()
        master.stop()


@pytest.mark.chaos
def test_asymmetric_rpc_delay_bounds_error(tmp_path):
    """Adversarial wire test: a client-side delay on every Heartbeat
    attempt sits BETWEEN the worker's t0 stamp and the master's t1
    stamp — a purely asymmetric up-leg delay, the worst case for NTP.
    The estimate may be biased by up to delay/2, but the published
    uncertainty (best-RTT/2) must cover the bias."""
    from scanner_tpu.engine.service import Master, Worker

    delay = 0.05
    faults.install(
        f"rpc.client.call:delay:seconds={delay}:method=Heartbeat")
    master = Master(db_path=str(tmp_path / "db"),
                    no_workers_timeout=30.0)
    worker = None
    try:
        worker = Worker(f"localhost:{master.port}",
                        db_path=str(tmp_path / "db"))
        deadline = time.time() + 20
        est = None
        while time.time() < deadline:
            with master._lock:
                offs = dict(master._clock_offsets)
            if offs:
                est = next(iter(offs.values()))
                if est.get("uncertainty", 0) >= delay / 2:
                    break
            time.sleep(0.1)
        assert faults.fired("rpc.client.call") > 0, \
            "delay fault never fired"
        assert est is not None
        # bias is bounded by delay/2 (+ loopback slop); the bound covers
        # it, so should_rebase still accepts this estimate only while
        # the uncertainty stays under the rebase threshold
        assert abs(est["offset"]) <= est["uncertainty"] + 0.01
        assert est["uncertainty"] >= delay / 2 - 0.01
    finally:
        faults.clear()
        if worker is not None:
            worker.stop()
        master.stop()
