"""Chaos suite: the cluster's robustness claims exercised under real,
deterministically injected faults (scanner_tpu/util/faults.py; see
docs/robustness.md for the failure model and recovery matrix).

Every test asserts two things the reference's fault suite
(py_test.py:788-1121) only implied:

  1. the fault actually FIRED — via the in-process rule counters /
     `scanner_tpu_faults_injected_total` (or the injected-crash exit
     code for dead processes), so no test passes vacuously;
  2. the job's output is bit-exact to a fault-free run — exactly-once,
     no duplicate or missing rows.

Fast deterministic tests run in tier-1 under the `chaos` marker; full
spawned-cluster runs (process crash, master restart, SIGTERM drain)
are additionally marked `slow`.
"""

import os
import signal
import struct
import subprocess
import sys
import threading
import time

import cloudpickle
import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         PerfParams, register_op)
from scanner_tpu.common import NullElement, StorageException
from scanner_tpu.engine.service import (MAX_TASK_FAILURES,
                                        MAX_TRANSIENT_FAILURES,
                                        PING_TIMEOUT, Master, Worker,
                                        _BulkJob, _is_transient_failure)
from scanner_tpu.storage import items
from scanner_tpu.storage import metadata as smd
from scanner_tpu.storage.backend import MemoryStorage
from scanner_tpu.util import faults
from scanner_tpu.util import metrics as _mx

# test kernels travel to worker subprocesses inside the job spec
cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.chaos

N_ROWS = 24


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="ChaosDouble")
class ChaosDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return _pk(2 * struct.unpack("<q", x)[0])


@register_op(name="ChaosSlowDouble")
class ChaosSlowDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        time.sleep(0.15)
        return _pk(2 * struct.unpack("<q", x)[0])


@register_op(name="ChaosRowLog")
class ChaosRowLog(Kernel):
    """Doubles the packed int AND appends it to a shared log file, so
    restart tests can assert exactly which rows were (re)executed."""

    def __init__(self, config, log_path: str = ""):
        super().__init__(config)
        self._log = log_path

    def execute(self, x: bytes) -> bytes:
        v = struct.unpack("<q", x)[0]
        time.sleep(0.1)
        with open(self._log, "a") as fh:
            fh.write(f"{v}\n")
        return _pk(2 * v)


EXPECT = [_pk(2 * (100 + i)) for i in range(N_ROWS)]


def _counter(name: str, **labels) -> float:
    """Current value of one series in the process-wide registry."""
    entry = _mx.registry().snapshot().get(name, {})
    for s in entry.get("samples", []):
        if s["labels"] == labels:
            return s["value"]
    return 0.0


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture()
def chaos_cluster(tmp_path):
    """Master + 2 in-process workers over a packed-int source table."""
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("chaos_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    master = Master(db_path=db_path, no_workers_timeout=30.0)
    addr = f"localhost:{master.port}"
    workers = [Worker(addr, db_path=db_path) for _ in range(2)]
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, workers, db_path, addr
    faults.clear()
    sc.stop()
    for w in workers:
        w.stop()
    master.stop()


def _run_golden(sc, out_name: str, op: str = "ChaosDouble", **perf_kw):
    """The golden pipeline: src -> packed-int kernel -> named sink.
    Returns the output rows as bytes (the bit-exactness witness)."""
    col = sc.io.Input([NamedStream(sc, "chaos_src")])
    col = getattr(sc.ops, op)(x=col)
    out = NamedStream(sc, out_name)
    sc.run(sc.io.Output(col, [out]), PerfParams.manual(2, 2, **perf_kw),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return [bytes(r) for r in out.load()]


# ---------------------------------------------------------------------------
# fault-registry units (no cluster)
# ---------------------------------------------------------------------------

def test_plan_parse_and_validation():
    rules = faults.parse_plan(
        "storage.write:raise:exc=storage:msg=boom:n=3:times=1;"
        "pipeline.eval:delay:seconds=2.5:match=task=0;"
        "rpc.client.call:raise:exc=unavailable:p=0.25:seed=7")
    assert [r.site for r in rules] == ["storage.write", "pipeline.eval",
                                      "rpc.client.call"]
    assert rules[0].exc == "storage" and rules[0].n == 3 \
        and rules[0].times == 1 and rules[0].msg == "boom"
    assert rules[1].seconds == 2.5 and rules[1].match == "task=0"
    assert rules[2].p == 0.25 and rules[2].seed == 7
    for bad in ("nosuch.site:raise",        # unknown site
                "storage.read:explode",     # unknown mode
                "storage.read:raise:zz=1",  # unknown key
                "storage.read:raise:n",     # not key=value
                "storage.read",             # missing mode
                "storage.write:corrupt",    # corrupt on a data-less site
                "storage.read:raise:exc=nope"):  # unknown exception
        with pytest.raises(faults.FaultPlanError):
            faults.parse_plan(bad)
    # every canned plan must stay parseable
    for name, spec in faults.NAMED_PLANS.items():
        assert faults.parse_plan(spec), name


def test_disabled_path_is_noop():
    assert not faults.ACTIVE
    blob = b"payload"
    assert faults.inject("storage.read", blob, detail="x") is blob
    faults.install("storage.read:corrupt")
    assert faults.ACTIVE
    faults.clear()
    assert not faults.ACTIVE
    assert faults.inject("storage.read", blob, detail="x") is blob


def test_trigger_determinism():
    r = faults.FaultRule(site="pipeline.eval", mode="raise", n=3)
    assert [r.should_fire("") for _ in range(5)] == \
        [False, False, True, False, False]
    r = faults.FaultRule(site="pipeline.eval", mode="raise", after=2)
    assert [r.should_fire("") for _ in range(5)] == \
        [False, False, True, True, True]
    r = faults.FaultRule(site="pipeline.eval", mode="raise", every=2,
                         times=2)
    assert [r.should_fire("") for _ in range(8)] == \
        [False, True, False, True, False, False, False, False]
    r = faults.FaultRule(site="pipeline.eval", mode="raise",
                         match="NextWork")
    assert not r.should_fire("Heartbeat")
    assert r.should_fire("NextWork")
    # p-mode: same seed -> same fire sequence, run to run
    seqs = []
    for _ in range(2):
        r = faults.FaultRule(site="pipeline.eval", mode="raise", p=0.5,
                             seed=9)
        seqs.append([r.should_fire("") for _ in range(64)])
    assert seqs[0] == seqs[1]
    assert any(seqs[0]) and not all(seqs[0])


def test_multi_rule_raise_does_not_overcount_fired():
    """When an earlier rule on a site raises, later rules that matched
    the same call never acted — fired() must not claim they did."""
    faults.install("storage.read:raise:exc=storage;"
                   "storage.read:corrupt")
    with pytest.raises(StorageException):
        faults.inject("storage.read", b"data", detail="x")
    by_mode = {r.mode: r.fired for r in faults.rules()}
    assert by_mode == {"raise": 1, "corrupt": 0}, by_mode
    assert faults.fired("storage.read") == 1
    s = MemoryStorage()
    items.write_item(s, "tables/1/output_0.bin",
                     [b"abc", NullElement(), b"defg"])
    base = _counter("scanner_tpu_item_corruptions_total")
    faults.install("storage.read:corrupt:match=tables/1/:n=1:times=1")
    with pytest.raises(items.ItemCorruptionError):
        items.read_item(s, "tables/1/output_0.bin")
    # the injected rot is spent: the re-read (what a requeued task
    # does) sees clean bytes
    assert items.read_item(s, "tables/1/output_0.bin") == \
        [b"abc", None, b"defg"]
    assert faults.fired("storage.read") == 1
    assert _counter("scanner_tpu_item_corruptions_total") == base + 1
    assert _counter("scanner_tpu_faults_injected_total",
                    site="storage.read", mode="corrupt") >= 1
    # corruption is classified transient: requeue, not blacklist strike
    assert _is_transient_failure(
        items.ItemCorruptionError("checksum mismatch"))


def test_header_rot_detected_by_checksum():
    """The crc spans the header too: a flipped bit in `nrows` would
    silently re-base every payload offset (garbage rows, no error) if
    only the body were checksummed."""
    s = MemoryStorage()
    items.write_item(s, "it", [b"abc", b"de", b"f"])
    raw = bytearray(s.read("it"))
    raw[8] ^= 0x01  # low byte of the nrows field: 3 -> 2
    s.write("it_rot", bytes(raw))
    with pytest.raises(items.ItemCorruptionError):
        items.read_item(s, "it_rot")


def test_item_checksum_algo_recorded_in_version(monkeypatch):
    """The checksum ALGORITHM travels in the version field: a zlib-
    fallback writer stamps version 3 (always verifiable), and a reader
    without google_crc32c skips verification of version-2 items
    instead of flagging valid data as corrupt with the wrong
    polynomial."""
    import zlib

    import numpy as np
    s = MemoryStorage()
    # version-3 item (zlib crc32), as a fallback writer would produce:
    # the crc spans the zeroed header + body
    sizes = np.array([3], np.uint64)
    body = sizes.tobytes() + b"xyz"
    hdr0 = struct.pack("<IIQI", items.MAGIC, items.VERSION_CRC32, 1, 0)
    v3 = struct.pack("<IIQI", items.MAGIC, items.VERSION_CRC32, 1,
                     zlib.crc32(hdr0 + body) & 0xFFFFFFFF) + body
    s.write("v3", v3)
    assert items.read_item(s, "v3") == [b"xyz"]
    # ...and a corrupted v3 item is still caught
    bad = bytearray(v3)
    bad[-1] ^= 0xFF
    s.write("v3bad", bytes(bad))
    with pytest.raises(items.ItemCorruptionError):
        items.read_item(s, "v3bad")

    # a crc32c (version-2) item read on a node WITHOUT google_crc32c:
    # verification is skipped (warned once), never misreported
    items.write_item(s, "v2", [b"abc"])
    monkeypatch.setattr(items, "_HAVE_CRC32C", False)
    monkeypatch.setattr(items, "_warned_unverifiable", False)
    assert items.read_item(s, "v2") == [b"abc"]


def test_item_v1_readable_without_checksum():
    import numpy as np
    s = MemoryStorage()
    sizes = np.array([3, items.NULL_SIZE], np.uint64)
    v1 = struct.pack("<IIQ", items.MAGIC, 1, 2) + sizes.tobytes() + b"xyz"
    s.write("old", v1)
    assert items.read_item(s, "old") == [b"xyz", None]
    assert items.item_num_rows(s, "old") == 2
    assert items.read_item_rows(s, "old", [0], sparsity_threshold=1) == \
        [b"xyz"]


def test_gcs_request_injection_rides_retry():
    from test_gcs import FakeGcsClient

    from scanner_tpu.storage import GcsStorage
    g = GcsStorage("bkt", "pfx", client=FakeGcsClient(),
                   backoff_base=0.001, backoff_cap=0.002)
    g.write("blob", b"data")
    # two transient connection failures per matching call window; the
    # backend's backoff (5 retries) must ride them out
    faults.install("gcs.request:raise:exc=connection:times=2")
    assert g.read("blob") == b"data"
    assert faults.fired("gcs.request") == 2


def test_transient_classifier():
    from scanner_tpu.engine.rpc import RpcError
    assert _is_transient_failure(StorageException("not found: x"))
    assert _is_transient_failure(RpcError("master gone"))
    assert _is_transient_failure(ConnectionError("reset"))
    assert _is_transient_failure(TimeoutError("deadline"))
    assert not _is_transient_failure(RuntimeError("kernel bug"))
    assert not _is_transient_failure(ValueError("bad shape"))


def test_transient_failures_requeue_without_strike(tmp_path):
    """Satellite: a transient FailedWork requeues strike-free; only past
    MAX_TRANSIENT_FAILURES do strikes (and eventually the blacklist)
    begin — a flaky dependency cannot blacklist a healthy job."""
    master = Master(db_path=str(tmp_path / "db"), no_workers_timeout=60.0)
    try:
        bulk = _BulkJob(bulk_id=0, spec_blob=b"", task_timeout=0.0)
        from collections import deque
        bulk.job_tasks[0] = {(0, 0)}
        bulk.job_sink_names[0] = []
        bulk.job_custom_sinks[0] = []
        bulk.job_output_rows[0] = 0
        bulk.queue[0] = deque([0])
        bulk.job_rr.append(0)
        bulk.total_tasks = 1
        with master._lock:
            master._bulk = bulk
            master._history[0] = bulk
        wid = master._rpc_register_worker({"address": "x"})["worker_id"]

        def fail_once(transient: bool):
            r = master._rpc_next_work({"worker_id": wid, "bulk_id": 0})
            assert r["status"] == "task"
            assert master._rpc_failed_work({
                "worker_id": wid, "bulk_id": 0, "job_idx": 0,
                "task_idx": 0, "attempt": r["attempt"],
                "transient": transient, "error": "injected"})["ok"]

        for i in range(MAX_TRANSIENT_FAILURES):
            fail_once(transient=True)
            assert not bulk.failures, f"strike on transient failure {i}"
            assert not bulk.blacklisted_jobs
            assert bulk.queue[0], "task not requeued"
        assert bulk.transient_failures[(0, 0)] == MAX_TRANSIENT_FAILURES
        # past the cap, "transient" failures strike like any other
        for i in range(MAX_TASK_FAILURES):
            assert not bulk.blacklisted_jobs
            fail_once(transient=True)
            assert bulk.failures.get((0, 0), 0) == i + 1
        assert bulk.blacklisted_jobs == {0}

        # deterministic failures strike immediately
        bulk2 = _BulkJob(bulk_id=1, spec_blob=b"", task_timeout=0.0)
        bulk2.job_tasks[0] = {(0, 0)}
        bulk2.job_sink_names[0] = []
        bulk2.job_custom_sinks[0] = []
        bulk2.job_output_rows[0] = 0
        bulk2.queue[0] = deque([0])
        bulk2.job_rr.append(0)
        bulk2.total_tasks = 1
        with master._lock:
            master._bulk = bulk2
            master._history[1] = bulk2
        r = master._rpc_next_work({"worker_id": wid, "bulk_id": 1})
        master._rpc_failed_work({
            "worker_id": wid, "bulk_id": 1, "job_idx": 0, "task_idx": 0,
            "attempt": r["attempt"], "transient": False,
            "error": "kernel bug"})
        assert bulk2.failures[(0, 0)] == 1
    finally:
        master.stop()


def test_rpc_server_logs_traceback(caplog):
    """Satellite: a handler exception logs its server-side stack at
    ERROR before being mapped to StatusCode.INTERNAL — previously only
    'type: msg' survived, and the stack was silently discarded."""
    import logging

    from scanner_tpu.engine.rpc import RpcClient, RpcError, RpcServer

    def boom(req):
        raise RuntimeError("handler exploded here")

    srv = RpcServer("ChaosTest", {"Boom": boom})
    srv.start()
    client = RpcClient(f"localhost:{srv.port}", "ChaosTest", timeout=5.0)
    try:
        with caplog.at_level(logging.ERROR, logger="scanner_tpu.rpc"):
            with pytest.raises(RpcError) as ei:
                client.call("Boom", retries=0)
        assert "INTERNAL" in str(ei.value)
        assert "RuntimeError: handler exploded here" in str(ei.value)
        assert "RPC Boom failed server-side" in caplog.text
        # the full traceback reached the server log
        assert "Traceback" in caplog.text
        assert "handler exploded here" in caplog.text
    finally:
        client.close()
        srv.stop()


def test_rpc_client_unavailable_storm_backoff():
    """An injected UNAVAILABLE storm at the client site is retried by
    the existing full-jitter backoff — the request never reached the
    server, so retrying cannot double-execute."""
    from scanner_tpu.engine.rpc import RpcClient, RpcServer

    srv = RpcServer("ChaosTest", {"Echo": lambda req: {"v": req["v"]}})
    srv.start()
    client = RpcClient(f"localhost:{srv.port}", "ChaosTest", timeout=5.0,
                       retries=4, backoff_base=0.01, backoff_cap=0.05)
    try:
        faults.install(
            "rpc.client.call:raise:exc=unavailable:match=Echo:times=2")
        assert client.call("Echo", v=7)["v"] == 7
        assert faults.fired("rpc.client.call") == 2
        assert _counter("scanner_tpu_faults_injected_total",
                        site="rpc.client.call", mode="raise") >= 2
    finally:
        client.close()
        srv.stop()


# ---------------------------------------------------------------------------
# in-process cluster chaos (tier-1)
# ---------------------------------------------------------------------------

def test_heartbeat_uses_short_timeout(chaos_cluster):
    """Satellite: heartbeat RPCs carry a ~2x PING_INTERVAL deadline, not
    the 30s client default — a hung master costs one beat, not a
    stale-worker removal."""
    _sc, _master, workers, _dbp, _addr = chaos_cluster
    w = workers[0]
    seen = []
    orig = w.master.try_call

    def recording(method, timeout=None, retries=None, **kw):
        seen.append((method, timeout))
        return orig(method, timeout=timeout, retries=retries, **kw)

    w.master.try_call = recording
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if any(m == "Heartbeat" for m, _t in seen):
            break
        time.sleep(0.05)
    hb = [(m, t) for m, t in seen if m == "Heartbeat"]
    assert hb, "no heartbeat observed"
    assert all(t == PING_TIMEOUT for _m, t in hb), hb


def test_chaos_sink_write_failure(chaos_cluster):
    """Fault class: a sink item write fails.  The failure is transient
    (storage), so the task requeues without a blacklist strike and the
    job completes bit-exact."""
    sc, _master, _workers, _dbp, _addr = chaos_cluster
    golden = _run_golden(sc, "c_sink_gold")
    assert golden == EXPECT
    strikes0 = _counter("scanner_tpu_blacklist_strikes_total")
    transient0 = _counter("scanner_tpu_transient_retries_total")
    faults.install("storage.write:raise:exc=storage:"
                   "msg=injected sink failure:match=output_:n=2:times=1")
    got = _run_golden(sc, "c_sink_fault")
    assert faults.fired("storage.write") == 1
    assert _counter("scanner_tpu_faults_injected_total",
                    site="storage.write", mode="raise") >= 1
    assert got == golden, "output not bit-exact after sink write fault"
    assert _counter("scanner_tpu_transient_retries_total") > transient0
    assert _counter("scanner_tpu_blacklist_strikes_total") == strikes0, \
        "transient sink failure counted a blacklist strike"


def test_chaos_corrupted_item_read(chaos_cluster):
    """Fault class: a stored item read returns corrupted bytes.  The
    crc32c check turns silent rot into ItemCorruptionError, the worker
    tags it transient, the requeued task re-reads clean bytes."""
    sc, master, workers, _dbp, addr = chaos_cluster
    golden = _run_golden(sc, "c_corrupt_gold", load_sparsity_threshold=100)
    # single dedicated worker so the read sequence per task is
    # deterministic: header ranged read (1st), dense whole read (2nd)
    for w in workers:
        w.stop()
    solo = Worker(addr, db_path=_dbp, num_load_workers=1,
                  num_save_workers=1)
    try:
        src_tid = sc._db.table_descriptor("chaos_src").id
        corrupt0 = _counter("scanner_tpu_item_corruptions_total")
        strikes0 = _counter("scanner_tpu_blacklist_strikes_total")
        faults.install(
            f"storage.read:corrupt:match=tables/{src_tid}/output_0.bin:"
            f"n=2:times=1")
        got = _run_golden(sc, "c_corrupt_fault",
                          load_sparsity_threshold=100)
        assert faults.fired("storage.read") == 1
        assert _counter("scanner_tpu_faults_injected_total",
                        site="storage.read", mode="corrupt") >= 1
        assert got == golden, "output not bit-exact after corrupted read"
        assert _counter("scanner_tpu_item_corruptions_total") == \
            corrupt0 + 1, "crc32c did not catch the injected corruption"
        assert _counter("scanner_tpu_blacklist_strikes_total") == strikes0
    finally:
        solo.stop()


def test_chaos_worker_hang_revocation(chaos_cluster):
    """Fault class: a worker wedges mid-evaluate while its heartbeat
    stays live.  Stale removal must NOT trigger (the worker is alive);
    the per-task timeout revokes the attempt and a sibling finishes it.
    The stale attempt's late completion is ignored by the attempt-id
    check, so the output stays exactly-once."""
    sc, master, workers, _dbp, _addr = chaos_cluster
    golden = _run_golden(sc, "c_hang_gold")
    revoked0 = _counter("scanner_tpu_task_revocations_total")
    faults.install("pipeline.eval:delay:seconds=5:n=1")
    got = _run_golden(sc, "c_hang_fault", task_timeout=1.0)
    assert faults.fired("pipeline.eval") == 1
    assert _counter("scanner_tpu_faults_injected_total",
                    site="pipeline.eval", mode="delay") >= 1
    assert got == golden, "output not bit-exact after hang+revocation"
    assert _counter("scanner_tpu_task_revocations_total") > revoked0, \
        "hung task was never revoked"
    with master._lock:
        active = [w for w in master._workers.values() if w.active]
    assert len(active) == 2, "a live (hung-but-heartbeating) worker " \
                             "was removed as stale"


def test_chaos_unavailable_storm_cluster(chaos_cluster):
    """Fault class: UNAVAILABLE storm on the control plane.  Every 2nd
    NextWork attempt fails at the transport; the client-side backoff
    rides each storm out within a single logical call, so the job
    needs no task retries at all."""
    sc, _master, _workers, _dbp, _addr = chaos_cluster
    golden = _run_golden(sc, "c_storm_gold")
    retries0 = _counter("scanner_tpu_retry_attempts_total",
                        site="rpc:NextWork")
    faults.install("rpc.client.call:raise:exc=unavailable:"
                   "match=NextWork:every=2:times=20")
    got = _run_golden(sc, "c_storm_fault", task_timeout=10.0)
    assert faults.fired("rpc.client.call") >= 10
    assert _counter("scanner_tpu_faults_injected_total",
                    site="rpc.client.call", mode="raise") >= 10
    assert got == golden, "output not bit-exact through the storm"
    assert _counter("scanner_tpu_retry_attempts_total",
                    site="rpc:NextWork") > retries0, \
        "storm never engaged the backoff path"


def test_chaos_drain_in_process(chaos_cluster):
    """SIGTERM drain semantics (hardening): a draining worker finishes
    its in-flight tasks, stops pulling, deregisters immediately (no
    stale-scan wait), and the sibling completes the job bit-exact."""
    sc, master, workers, _dbp, _addr = chaos_cluster
    golden = _run_golden(sc, "c_drain_gold")
    drains0 = _counter("scanner_tpu_worker_drains_total")
    victim = workers[0]
    result = {}

    def run_job():
        try:
            result["rows"] = _run_golden(sc, "c_drain_fault",
                                         op="ChaosSlowDouble")
        except Exception as e:  # noqa: BLE001
            result["error"] = e

    t = threading.Thread(target=run_job)
    t.start()
    time.sleep(1.0)  # let the job spin up and assign tasks
    victim.drain()
    t.join(timeout=60)
    assert not t.is_alive(), "job wedged after drain"
    assert "error" not in result, result.get("error")
    assert result["rows"] == golden
    # drained worker deregistered without waiting for the stale scan
    deadline = time.time() + 10
    while time.time() < deadline:
        with master._lock:
            w = master._workers.get(victim.worker_id)
            if w is not None and not w.active:
                break
        time.sleep(0.1)
    with master._lock:
        assert not master._workers[victim.worker_id].active, \
            "drained worker still registered as active"
    assert _counter("scanner_tpu_worker_drains_total") == drains0 + 1
    assert victim._shutdown.is_set(), "drained worker did not shut down"


# ---------------------------------------------------------------------------
# spawned-cluster chaos (slow)
# ---------------------------------------------------------------------------

def _spawn_env(extra=None):
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SCANNER_TPU_FAULTS", None)
    env.update(extra or {})
    return env


def _spawn_worker(addr, db_path, plan=None):
    spawn = os.path.join(os.path.dirname(__file__), "spawn_worker.py")
    extra = {"SCANNER_TPU_FAULTS": plan} if plan else None
    return subprocess.Popen(
        [sys.executable, spawn, addr, db_path], env=_spawn_env(extra),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_chaos_worker_crash_midtask(tmp_path):
    """Fault class: a worker PROCESS dies mid-task (os._exit — no
    cleanup, like a kill -9 or an OOM).  The stale scan deactivates it,
    its tasks requeue, the surviving worker finishes, and the output is
    bit-exact."""
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("chaos_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    master = Master(db_path=db_path, no_workers_timeout=60.0)
    addr = f"localhost:{master.port}"
    sc = Client(db_path=db_path, master=addr)
    survivor = _spawn_worker(addr, db_path)
    victim = None
    try:
        # golden BEFORE the victim exists: its armed plan would fire
        # during any run it participates in
        golden = _run_golden(sc, "c_crash_gold", op="ChaosSlowDouble")
        assert golden == EXPECT
        victim = _spawn_worker(addr, db_path,
                               plan=faults.NAMED_PLANS["worker-crash"])
        # wait for the victim to register so it actually takes tasks
        deadline = time.time() + 30
        while time.time() < deadline:
            with master._lock:
                if sum(1 for w in master._workers.values()
                       if w.active) >= 2:
                    break
            time.sleep(0.1)
        got = _run_golden(sc, "c_crash_fault", op="ChaosSlowDouble")
        # the injected crash fired: the victim died with the chaos exit
        # code (the cross-process twin of the faults-injected counter)
        assert victim.wait(timeout=30) == faults.CRASH_EXIT_CODE
        assert got == golden, "output not bit-exact after worker crash"
    finally:
        for p in (victim, survivor):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
        sc.stop()
        master.stop()


@pytest.mark.slow
def test_chaos_master_crash_recovery(tmp_path):
    """Fault class + satellite: the MASTER dies mid-bulk (injected
    crash in the FinishedWork handler).  A restarted master on the same
    db_path recovers the bulk from its checkpoint (_recover_bulk), the
    surviving worker re-registers and finishes, tasks in the persisted
    done-set are NOT re-executed, and the output is bit-exact."""
    import socket

    db_path = str(tmp_path / "db")
    log = str(tmp_path / "rows.log")
    seed = Client(db_path=db_path)
    seed.new_table("chaos_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    seed.stop()

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    addr = f"localhost:{port}"
    spawn = os.path.join(os.path.dirname(__file__), "spawn_master.py")

    def spawn_master(plan=None):
        extra = {"SCANNER_TPU_FAULTS": plan} if plan else None
        return subprocess.Popen(
            [sys.executable, spawn, db_path, str(port)],
            env=_spawn_env(extra),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    # crash handling the 4th FinishedWork: 3 completions are in the
    # persisted done-set (checkpoint_frequency=1), the 4th is lost and
    # must re-run after recovery
    m1 = spawn_master(plan=faults.NAMED_PLANS["master-crash"])
    state = {}

    def respawner():
        state["rc1"] = m1.wait(timeout=120)
        # the progress snapshot now lives at the generation-scoped
        # sealed path (engine/journal.py); the helper resolves it
        from scanner_tpu.engine import journal as _journal
        from scanner_tpu.storage.backend import PosixStorage
        prog = _journal.load_bulk_progress(PosixStorage(db_path))
        state["done_at_crash"] = Master._decode_task_set(
            prog["done_runs"]) if prog else set()
        state["rows_at_crash"] = open(log).read().splitlines()
        time.sleep(0.5)
        state["m2"] = spawn_master()

    worker = None
    sc = None
    try:
        sc = Client(db_path=db_path, master=addr)
        worker = Worker(addr, db_path=db_path)
        rt = threading.Thread(target=respawner)
        rt.start()
        col = sc.io.Input([NamedStream(sc, "chaos_src")])
        col = sc.ops.ChaosRowLog(x=col, log_path=log)
        out = NamedStream(sc, "c_mcrash_out")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.manual(2, 2, checkpoint_frequency=1),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        rt.join(timeout=60)
        assert not rt.is_alive(), "master never crashed/respawned"
        # the injected crash fired (cross-process exit-code witness)
        assert state["rc1"] == faults.CRASH_EXIT_CODE
        assert state["done_at_crash"], "no tasks persisted before crash"

        assert [bytes(r) for r in out.load()] == EXPECT
        assert out.committed()
        # rows of tasks in the persisted done-set ran exactly once: the
        # recovered master did not re-execute them
        counts = {}
        for line in open(log).read().splitlines():
            counts[int(line)] = counts.get(int(line), 0) + 1
        for (_j, t) in state["done_at_crash"]:
            for row in (100 + 2 * t, 100 + 2 * t + 1):
                assert counts.get(row, 0) == 1, \
                    f"row {row} of checkpointed task {t} ran " \
                    f"{counts.get(row, 0)} times"
        assert all(counts.get(100 + i, 0) >= 1 for i in range(N_ROWS))
    finally:
        if worker is not None:
            worker.stop()
        if sc is not None:
            sc.stop()
        for p in (m1, state.get("m2")):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


@pytest.mark.slow
def test_chaos_sigterm_drain_spawned(tmp_path):
    """Hardening e2e: SIGTERM to a worker PROCESS mid-job (kubernetes
    pod termination) drains it — in-flight tasks finish, it
    deregisters, exits 0 within the grace period — and the sibling
    completes the job bit-exact."""
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("chaos_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    master = Master(db_path=db_path, no_workers_timeout=60.0)
    addr = f"localhost:{master.port}"
    sc = Client(db_path=db_path, master=addr)
    survivor = _spawn_worker(addr, db_path)
    victim = _spawn_worker(addr, db_path)
    try:
        golden = _run_golden(sc, "c_term_gold", op="ChaosSlowDouble")

        def terminator():
            time.sleep(1.5)
            victim.send_signal(signal.SIGTERM)

        tt = threading.Thread(target=terminator)
        tt.start()
        got = _run_golden(sc, "c_term_fault", op="ChaosSlowDouble")
        tt.join()
        assert got == golden, "output not bit-exact across drain"
        # clean exit, well inside the deploy.py terminationGracePeriod
        assert victim.wait(timeout=30) == 0, "drained worker died dirty"
    finally:
        for p in (victim, survivor):
            if p.poll() is None:
                p.kill()
                p.wait()
        sc.stop()
        master.stop()


def test_chaos_run_cli_lists_plans():
    """tools/chaos_run.py enumerates the canned plans (full runs are
    exercised by the slow tests; --list keeps the CLI import-checked
    in tier-1)."""
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "chaos_run.py"), "--list"],
        env=_spawn_env(), capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    for name in faults.NAMED_PLANS:
        assert name in r.stdout
