"""Gang-scheduled multi-host execution (docs/robustness.md §Gang
scheduling; scanner_tpu/engine/gang.py + engine/service.py).

Layers:
  * pure units — shard math, digest determinism, journal gang-record
    helpers;
  * in-process master units — formation + role minting, the
    synthetic-clock form-timeout path (a smaller gang forms on the
    pooled survivors), stale-epoch NACKs on BOTH sides (master refuses
    stale member reports; the worker refuses a stale master's gang
    assignment), abort-on-{GangFailed, preemption, worker loss,
    task timeout}, the transient-cap backstop, and journal round-trip +
    master-failover-mid-gang recovery with no double-commit;
  * spawned e2e (slow) — a real gang bulk over a spawned cluster with
    one member SIGKILLed mid-collective (the `gang-host-loss` plan):
    the gang re-forms at a higher epoch on the survivors, output is
    bit-exact, zero strikes; plus the jax-level rank-death + re-form
    harness reusing tests/multihost_child.py.
"""

import os
import struct
import subprocess
import sys
import time

import cloudpickle
import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         PerfParams, register_op)
from scanner_tpu.engine import gang as egang
from scanner_tpu.engine import journal
from scanner_tpu.engine.service import (MASTER_SERVICE,
                                        MAX_TASK_FAILURES,
                                        MAX_TRANSIENT_FAILURES, Master,
                                        Worker)
from scanner_tpu.util import faults
from scanner_tpu.util import metrics as _mx

cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.chaos

N_ROWS = 8


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="GangDouble")
class GangDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return _pk(2 * struct.unpack("<q", x)[0])


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    if labels:
        for s in entry.get("samples", []):
            if s["labels"] == labels:
                return s["value"]
        return 0.0
    return sum(s["value"] for s in entry.get("samples", []))


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# pure units
# ---------------------------------------------------------------------------

def test_shard_range_partition():
    """Shards are contiguous, disjoint, and cover [0, n) for any
    (n, num_processes) — the per-host digest staging keys off this."""
    for n in (0, 1, 5, 8, 17):
        for procs in (1, 2, 3, 4, 7):
            spans = [egang.shard_range(n, p, procs)
                     for p in range(procs)]
            assert spans[0][0] == 0
            assert spans[-1][1] == n
            for (a_lo, a_hi), (b_lo, b_hi) in zip(spans, spans[1:]):
                assert a_hi == b_lo and a_lo <= a_hi


def test_digest_rows_deterministic():
    rows = [b"abc", b"def", bytearray(b"ghi")]
    assert egang._digest_rows(rows) == egang._digest_rows(list(rows))
    assert egang._digest_rows([b"abc"]) != egang._digest_rows([b"abd"])
    import numpy as np
    arr_rows = [np.arange(4, dtype=np.int32), np.ones((2, 2))]
    assert egang._digest_rows(arr_rows) == egang._digest_rows(arr_rows)
    # shard sums compose: sum of shard digests == digest accumulated
    # over all rows (mod 2**32), which is what member 0 cross-checks
    full = egang._digest_rows(rows)
    lo, hi = egang.shard_range(len(rows), 0, 2)
    lo2, hi2 = egang.shard_range(len(rows), 1, 2)
    assert (egang._digest_rows(rows[lo:hi])
            + egang._digest_rows(rows[lo2:hi2])) & 0xFFFFFFFF == full


def test_journal_gang_epoch_high_water():
    recs = [{"t": "done", "j": 0, "k": 1},
            {"t": "gang", "g": 0, "e": 3, "j": 0, "k": 2},
            {"t": "gang_abort", "g": 0, "e": 3},
            {"t": "gang", "g": 1, "e": 5, "j": 0, "k": 2}]
    assert journal.gang_epoch_high_water(recs) == 5
    assert journal.gang_epoch_high_water([]) == 0


# ---------------------------------------------------------------------------
# in-process master units
# ---------------------------------------------------------------------------

def _seed_db(tmp_path):
    db_path = str(tmp_path / "db")
    sc = Client(db_path=db_path)
    sc.new_table("gang_src", ["output"],
                 [[_pk(100 + i)] for i in range(N_ROWS)])
    return sc, db_path


def _spec_blob(sc, out_name, gang_hosts=2, **perf_kw):
    col = sc.io.Input([NamedStream(sc, "gang_src")])
    col = sc.ops.GangDouble(x=col)
    out = NamedStream(sc, out_name)
    node = sc.io.Output(col, [out])
    return cloudpickle.dumps({
        "outputs": [node],
        "perf": PerfParams.manual(2, 4, gang_hosts=gang_hosts,
                                  **perf_kw),
        "cache_mode": CacheMode.Overwrite.value})


def _register(master, n, base_port=7100):
    return [master._rpc_register_worker(
        {"address": "", "gang_address": f"localhost:{base_port + i}"}
    )["worker_id"] for i in range(n)]


def _form(master, bid, wids):
    """Pull until a gang forms; returns {wid: role} for every member."""
    roles = {}
    deadline = time.time() + 10
    while time.time() < deadline and len(roles) < len(wids):
        for wid in wids:
            r = master._rpc_next_work({"worker_id": wid,
                                       "bulk_id": bid})
            if r.get("status") == "gang":
                roles[wid] = r
        if not roles:
            time.sleep(0.02)
    assert roles, "no gang formed"
    return roles


def test_gang_formation_roles_and_coordinator(tmp_path):
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_form"),
                              "token": "t"})["bulk_id"]
        # first pull pools; second completes the gang; both get roles
        r0 = m._rpc_next_work({"worker_id": w0, "bulk_id": bid})
        assert r0["status"] == "wait"
        roles = _form(m, bid, [w0, w1])
        a, b = roles[w0], roles[w1]
        assert a["gang_id"] == b["gang_id"] and a["epoch"] == b["epoch"]
        assert {a["process_id"], b["process_id"]} == {0, 1}
        assert a["num_processes"] == 2
        # member 0's advertised gang address coordinates
        m0 = w0 if a["process_id"] == 0 else w1
        with m._lock:
            g = m._bulk.gangs[a["gang_id"]]
            assert g.members[0] == m0
            assert a["coordinator"] == \
                m._workers[m0].gang_address
        # the gang root span context is shared by every member
        assert a["traceparent"] == b["traceparent"]
        assert _counter("scanner_tpu_gang_formed_total") >= 1
    finally:
        m.stop()
        sc.stop()


def test_form_timeout_forms_smaller_gang(tmp_path):
    """The loss-tolerant path: gang_hosts=3 but only one worker is
    pooled — after [gang] form_timeout_s the master forms a singleton
    gang instead of waiting for capacity that is gone."""
    sc, db_path = _seed_db(tmp_path)
    old = egang.form_timeout_s()
    egang.set_form_timeout_s(0.05)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        (w0,) = _register(m, 1)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_small",
                                                 gang_hosts=3),
                              "token": "t"})["bulk_id"]
        r = m._rpc_next_work({"worker_id": w0, "bulk_id": bid})
        assert r["status"] == "wait"  # pool opened this instant
        time.sleep(0.1)
        r = m._rpc_next_work({"worker_id": w0, "bulk_id": bid})
        assert r["status"] == "gang", r
        assert r["num_processes"] == 1 and r["process_id"] == 0
    finally:
        egang.set_form_timeout_s(old)
        m.stop()
        sc.stop()


def test_stale_epoch_nack_master_side(tmp_path):
    """Every gang RPC is fenced by (gang_id, epoch): stale member
    reports — completion, ack, failure — answer gang_stale and are
    never applied."""
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_nack"),
                              "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        r = roles[w0]
        m0 = w0 if roles[w0]["process_id"] == 0 else w1
        m1 = w1 if m0 == w0 else w0
        base = dict(bulk_id=bid, gang_id=r["gang_id"],
                    job_idx=r["job_idx"], task_idx=r["task_idx"],
                    attempt=r["attempt"])
        nacks0 = _counter("scanner_tpu_gang_stale_nacks_total")
        # stale epoch on every gang RPC -> NACK, state untouched
        stale = dict(base, epoch=r["epoch"] - 1)
        assert m._rpc_gang_member_done(
            dict(stale, worker_id=m1)).get("gang_stale")
        assert m._rpc_gang_failed(
            dict(stale, worker_id=m1,
                 transient=True)).get("gang_stale")
        assert m._rpc_finished_work(
            dict(stale, worker_id=m0)).get("gang_stale")
        # a non-coordinator member may not complete the task, even at
        # the live epoch (single-writer commit)
        assert m._rpc_finished_work(
            dict(base, epoch=r["epoch"],
                 worker_id=m1)).get("gang_stale")
        assert _counter("scanner_tpu_gang_stale_nacks_total") \
            >= nacks0 + 4
        with m._lock:
            assert not m._bulk.done
            assert r["gang_id"] in m._bulk.gangs
        # the live writer's completion lands
        ok = m._rpc_finished_work(dict(base, epoch=r["epoch"],
                                       worker_id=m0))
        assert ok == {"ok": True}
        # a survivor's ack AFTER the writer committed is acknowledged
        # quietly (the healthy tail), not counted as fence traffic
        tail = m._rpc_gang_member_done(dict(base, epoch=r["epoch"],
                                            worker_id=m1))
        assert tail == {"ok": True}
        with m._lock:
            assert (r["job_idx"], r["task_idx"]) in m._bulk.done
            assert not m._bulk.held
    finally:
        m.stop()
        sc.stop()


def test_stale_master_gang_assignment_nacked_worker_side(tmp_path):
    """The worker side of 'both sides': a gang role stamped by a
    superseded master generation is NACKed by the worker's latch — a
    stale master cannot convene a gang."""
    _sc, db_path = _seed_db(tmp_path)
    _sc.stop()
    master = Master(db_path=db_path, no_workers_timeout=60.0)
    worker = Worker(f"localhost:{master.port}", db_path=db_path)
    try:
        gen = master.generation
        orig = worker.master.try_call

        def fake(method, timeout=None, retries=None, **kw):
            if method == "Heartbeat":
                return {"reregister": False, "active_bulk": 7,
                        "generation": gen + 1}
            if method == "NextWork":
                # the stale master still hands out gang roles
                return {"status": "gang", "gang_id": 0, "epoch": 1,
                        "process_id": 0, "num_processes": 2,
                        "coordinator": "localhost:1", "job_idx": 0,
                        "task_idx": 0, "attempt": 0,
                        "generation": gen}
            return orig(method, timeout=timeout, retries=retries,
                        **kw)

        worker.master.try_call = fake
        deadline = time.time() + 10
        while time.time() < deadline and worker._gen.highest() <= gen:
            time.sleep(0.05)
        base = _counter("scanner_tpu_stale_master_rejections_total",
                        side="worker")
        worker._hb_reply = {"active_bulk": 7, "generation": gen + 1}
        assert worker._next_gang(7) == "wait", \
            "stale-generation gang role was accepted"
        assert _counter("scanner_tpu_stale_master_rejections_total",
                        side="worker") > base
    finally:
        worker.stop()
        master.stop()


def test_gang_failed_aborts_and_reforms_at_higher_epoch(tmp_path):
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_reform"),
                              "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        r = roles[w0]
        strikes0 = _counter("scanner_tpu_blacklist_strikes_total")
        aborted0 = _counter("scanner_tpu_gang_aborted_total",
                            reason="member_failed:collective")
        ok = m._rpc_gang_failed({
            "worker_id": w1, "bulk_id": bid, "gang_id": r["gang_id"],
            "epoch": r["epoch"], "stage": "collective",
            "transient": True, "error": "peer lost"})
        assert ok == {"ok": True}
        with m._lock:
            b = m._bulk
            assert not b.gangs and not b.outstanding and not b.held
            assert b.gang_epoch == r["epoch"] + 1
        assert _counter("scanner_tpu_gang_aborted_total",
                        reason="member_failed:collective") \
            == aborted0 + 1
        # zero strikes on the survivors (strike-free requeue)
        assert _counter("scanner_tpu_blacklist_strikes_total") \
            == strikes0
        # re-formation runs at a strictly higher epoch and counts as a
        # reform
        reforms0 = _counter("scanner_tpu_gang_reforms_total")
        roles2 = _form(m, bid, [w0, w1])
        r2 = next(iter(roles2.values()))
        assert r2["epoch"] > r["epoch"]
        assert _counter("scanner_tpu_gang_reforms_total") \
            == reforms0 + 1
    finally:
        m.stop()
        sc.stop()


def test_fenced_master_preemption_keeps_gang(tmp_path):
    """Regression (scanner-check SC402): a superseded master that
    hears a preemption notice marks the worker preempting — volatile
    assignment fence, safe on any master — but must NOT abort its
    gangs: the epoch bump is journaled durable state the successor
    owns now."""
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_fence_pre"),
                              "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        r = roles[w0]
        aborted0 = _counter("scanner_tpu_gang_aborted_total",
                            reason="preempted")
        m._fence.set()
        m._rpc_heartbeat({"worker_id": w1, "preempting": True})
        with m._lock:
            assert m._workers[w1].preempting
            assert m._bulk.gangs, \
                "fenced master aborted a gang (durable epoch bump " \
                "past the fence)"
            assert m._bulk.gang_epoch == r["epoch"]
        assert _counter("scanner_tpu_gang_aborted_total",
                        reason="preempted") == aborted0
    finally:
        m.stop()
        sc.stop()


def test_preemption_notice_aborts_member_gang(tmp_path):
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_preempt"),
                              "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        r = roles[w0]
        hb = m._rpc_heartbeat({"worker_id": w1, "preempting": True})
        # the preempted worker's gang is gone from its liveness list
        assert hb.get("gangs") == []
        with m._lock:
            assert not m._bulk.gangs
            assert m._bulk.gang_epoch == r["epoch"] + 1
        assert _counter("scanner_tpu_gang_aborted_total",
                        reason="preempted") >= 1
    finally:
        m.stop()
        sc.stop()


def test_worker_loss_aborts_member_gang(tmp_path):
    """A dead NON-coordinator member is invisible to the outstanding
    map (member 0 owns the assignment) — the requeue path must still
    abort the gang via membership."""
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_loss"),
                              "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        r = roles[w0]
        m1 = w1 if roles[w1]["process_id"] != 0 else w0  # non-coord
        with m._lock:
            m._workers[m1].active = False
            m._requeue_worker_tasks(m1)
            b = m._bulk
            assert not b.gangs
            assert b.gang_epoch == r["epoch"] + 1
            assert b.q_has_work() and not b.outstanding and not b.held
        assert _counter("scanner_tpu_gang_aborted_total",
                        reason="member_lost") >= 1
    finally:
        m.stop()
        sc.stop()


def test_gang_task_timeout_aborts_whole_gang(tmp_path):
    sc, db_path = _seed_db(tmp_path)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0, w1 = _register(m, 2)
        bid = m._rpc_new_job(
            {"spec": _spec_blob(sc, "g_tmo", task_timeout=0.6),
             "token": "t"})["bulk_id"]
        roles = _form(m, bid, [w0, w1])
        r = roles[w0]
        deadline = time.time() + 10
        while time.time() < deadline:
            with m._lock:
                if not m._bulk.gangs:
                    break
            time.sleep(0.1)
        with m._lock:
            assert not m._bulk.gangs, "timeout scan never aborted"
            assert m._bulk.gang_epoch >= r["epoch"] + 1
        assert _counter("scanner_tpu_gang_aborted_total",
                        reason="timeout") >= 1
    finally:
        m.stop()
        sc.stop()


def test_gang_abort_cap_terminates_bulk(tmp_path):
    """A gang that can never complete must not re-form forever: past
    the transient cap, aborts start striking and the job blacklists —
    the bulk terminates with an error instead of spinning."""
    sc, db_path = _seed_db(tmp_path)
    old = egang.form_timeout_s()
    egang.set_form_timeout_s(0.01)
    m = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        (w0,) = _register(m, 1)
        bid = m._rpc_new_job({"spec": _spec_blob(sc, "g_cap",
                                                 gang_hosts=1),
                              "token": "t"})["bulk_id"]
        for _ in range(MAX_TRANSIENT_FAILURES + MAX_TASK_FAILURES + 2):
            r = None
            deadline = time.time() + 5
            while r is None and time.time() < deadline:
                got = m._rpc_next_work({"worker_id": w0,
                                        "bulk_id": bid})
                if got.get("status") == "gang":
                    r = got
                elif got.get("status") in ("none", "done"):
                    r = "over"
                else:
                    time.sleep(0.01)
            if r == "over" or r is None:
                break
            m._rpc_gang_failed({
                "worker_id": w0, "bulk_id": bid,
                "gang_id": r["gang_id"], "epoch": r["epoch"],
                "stage": "rendezvous", "transient": True,
                "error": "never forms"})
        with m._lock:
            b = m._bulk
            assert b.finished and b.blacklisted_jobs == {0}
            assert "exhausted" in b.error
    finally:
        egang.set_form_timeout_s(old)
        m.stop()
        sc.stop()


def test_gang_journal_roundtrip_and_failover_no_double_commit(tmp_path):
    """Master failover mid-gang: the successor restores the done-set
    AND the gang epoch's high-water mark from the journal; the
    pre-failover gang's completion NACKs on the successor (no
    double-commit), the in-flight task re-forms and completes."""
    sc, db_path = _seed_db(tmp_path)
    m1 = Master(db_path=db_path, no_workers_timeout=60.0)
    w0, w1 = _register(m1, 2)
    bid = m1._rpc_new_job({"spec": _spec_blob(sc, "g_fo"),
                           "token": "tok-G"})["bulk_id"]
    # gang A completes its task (the done record + gang record journal)
    roles = _form(m1, bid, [w0, w1])
    ra = roles[w0]
    m0a = w0 if roles[w0]["process_id"] == 0 else w1
    assert m1._rpc_finished_work({
        "worker_id": m0a, "bulk_id": bid, "gang_id": ra["gang_id"],
        "epoch": ra["epoch"], "job_idx": ra["job_idx"],
        "task_idx": ra["task_idx"],
        "attempt": ra["attempt"]}) == {"ok": True}
    # gang B forms and is IN FLIGHT when the master dies
    roles_b = _form(m1, bid, [w0, w1])
    rb = roles_b[w0]
    m0b = w0 if roles_b[w0]["process_id"] == 0 else w1
    m1.stop()  # abrupt: no checkpoint clear

    m2 = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        with m2._lock:
            b = m2._bulk
            assert b is not None and b.bulk_id == bid
            assert b.gang_hosts == 2
            # journaled completion restored, in-flight task requeued
            assert (ra["job_idx"], ra["task_idx"]) in b.done
            assert (rb["job_idx"], rb["task_idx"]) not in b.done
            assert b.q_has_work()
            # epoch fence restored at or above gang B's epoch
            assert b.gang_epoch >= rb["epoch"]
            done0 = len(b.done)
        # the pre-failover writer's late completion NACKs: no gang with
        # that (gang_id, epoch) exists on the successor
        late = m2._rpc_finished_work({
            "worker_id": m0b, "bulk_id": bid,
            "gang_id": rb["gang_id"], "epoch": rb["epoch"],
            "job_idx": rb["job_idx"], "task_idx": rb["task_idx"],
            "attempt": rb["attempt"]})
        assert late.get("gang_stale"), late
        with m2._lock:
            assert len(m2._bulk.done) == done0, "double-commit!"
        # fresh workers re-form the task at a strictly higher epoch
        # and complete it exactly once
        v0, v1 = _register(m2, 2)
        roles_c = _form(m2, bid, [v0, v1])
        rc = roles_c[v0]
        assert rc["epoch"] > rb["epoch"]
        m0c = v0 if roles_c[v0]["process_id"] == 0 else v1
        assert m2._rpc_finished_work({
            "worker_id": m0c, "bulk_id": bid,
            "gang_id": rc["gang_id"], "epoch": rc["epoch"],
            "job_idx": rc["job_idx"], "task_idx": rc["task_idx"],
            "attempt": rc["attempt"]}) == {"ok": True}
        with m2._lock:
            assert len(m2._bulk.done) == done0 + 1
    finally:
        m2.stop()
        sc.stop()


# ---------------------------------------------------------------------------
# spawned e2e (slow)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_gang_e2e_host_loss_reforms_bit_exact(tmp_path):
    """The headline drill as a test: a spawned master + 2 workers run a
    gang bulk; worker 0 dies the moment its first member enters the
    cross-host collective (gang-host-loss plan; the runner dies with it
    via pdeathsig).  The gang must abort, re-form at a higher epoch on
    the survivor, and the output must be bit-exact — with zero
    blacklist strikes."""
    from scanner_tpu.engine.rpc import wait_for_server
    from scanner_tpu.util.jaxenv import cpu_only_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("gang_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    env = cpu_only_env()
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SCANNER_TPU_FAULTS", None)
    env["SCANNER_TPU_GANG_INIT_TIMEOUT"] = "30"
    env["SCANNER_TPU_GANG_FORM_TIMEOUT"] = "6"
    port = _free_port()
    addr = f"localhost:{port}"

    def spawn(script, argv, plan=None):
        e = dict(env)
        if plan:
            e["SCANNER_TPU_FAULTS"] = plan
        return subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", script),
             *argv], env=e)

    procs = [spawn("spawn_master.py", [db_path, str(port)])]
    procs.append(spawn("spawn_worker.py", [addr, db_path],
                       plan=faults.NAMED_PLANS["gang-host-loss"]))
    procs.append(spawn("spawn_worker.py", [addr, db_path]))
    sc = None
    try:
        wait_for_server(addr, MASTER_SERVICE, timeout=60.0)
        sc = Client(db_path=db_path, master=addr)
        deadline = time.time() + 60
        while time.time() < deadline \
                and sc.job_status().get("num_workers", 0) < 2:
            time.sleep(0.25)
        col = sc.io.Input([NamedStream(sc, "gang_src")])
        col = sc.ops.GangDouble(x=col)
        out = NamedStream(sc, "gang_out")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.manual(4, N_ROWS // 2, gang_hosts=2),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        rows = [bytes(r) for r in out.load()]
        assert rows == [_pk(2 * (100 + i)) for i in range(N_ROWS)]
        # the armed worker died with the injected crash code
        time.sleep(0.5)
        crashed = [p for p in procs
                   if p.poll() == faults.CRASH_EXIT_CODE]
        assert crashed, "gang.collective crash never fired"
        snap = sc.metrics()

        def tot(name):
            return sum(s.get("value", 0) for s in
                       snap.get(name, {}).get("samples", []))

        assert tot("scanner_tpu_gang_aborted_total") >= 1
        assert tot("scanner_tpu_gang_reforms_total") >= 1
        assert tot("scanner_tpu_gang_epoch") >= 2
        assert tot("scanner_tpu_blacklist_strikes_total") == 0
    finally:
        if sc is not None:
            sc.stop()
        seed.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


@pytest.mark.slow
def test_multihost_sigkill_then_reform_same_port():
    """The jax-level loss-tolerant re-forming proof, reusing the
    tests/multihost_child.py harness: SIGKILL one rank mid-collective
    (after it joined the runtime) — the group must never complete —
    then a FRESH, smaller group re-forms on the SAME coordinator port
    and completes (what a re-formed gang epoch does)."""
    from multihost_child import free_port, spawn_multihost

    port = free_port()
    with pytest.raises(RuntimeError, match="rank death confirmed"):
        spawn_multihost(n_processes=2, devices_per_process=2,
                        timeout=240, sigkill_rank=1, port=port)
    outs = spawn_multihost(n_processes=1, devices_per_process=2,
                           timeout=240, port=port)
    assert any("MULTIHOST_LOSS" in o for o in outs)
