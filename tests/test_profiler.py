"""Profiler levels + bounded buffering (reference profiler.h:40-86 levels,
rpc.proto:270-275 profiler_level)."""

import numpy as np

from scanner_tpu.util.profiler import Profiler


def test_level_filtering():
    p = Profiler(level=0)
    with p.span("coarse", level=0):
        pass
    with p.span("detail", level=1):
        pass
    p.add_interval("verbose", 0.0, 1.0, level=2)
    names = [iv.name for iv in p.intervals()]
    assert names == ["coarse"]


def test_interval_cap_counts_drops():
    p = Profiler(max_intervals=5)
    for i in range(9):
        with p.span(f"s{i}"):
            pass
    assert len(p.intervals()) == 5
    assert p.counters["profiler_dropped"] == 4


def test_profiler_level_knob(sc=None):
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    import scanner_tpu.kernels
    from scanner_tpu import video as scv
    import tempfile, os
    root = tempfile.mkdtemp(prefix="prof_")
    vid = os.path.join(root, "v.mp4")
    scv.synthesize_video(vid, num_frames=16, width=64, height=48, fps=24)
    c = Client(db_path=os.path.join(root, "db"))
    try:
        def run(level, name):
            frame = c.io.Input([NamedVideoStream(c, "t", path=vid)])
            out = NamedStream(c, name)
            jid = c.run(c.io.Output(c.ops.Histogram(frame=frame), [out]),
                        PerfParams.manual(8, 16, profiler_level=level),
                        cache_mode=CacheMode.Overwrite, show_progress=False)
            return c.get_profile(jid).statistics()

        st0 = run(0, "p0")
        st1 = run(1, "p1")
        # level 0: coarse stage spans only; level 1 adds per-op detail
        assert "load" in st0 and "evaluate" in st0 and "save" in st0
        assert "evaluate:Histogram" not in st0
        assert "evaluate:Histogram" in st1
    finally:
        c.stop()
