"""Profiler levels + bounded buffering (reference profiler.h:40-86 levels,
rpc.proto:270-275 profiler_level)."""

import numpy as np

from scanner_tpu.util.profiler import Profiler


def test_level_filtering():
    p = Profiler(level=0)
    with p.span("coarse", level=0):
        pass
    with p.span("detail", level=1):
        pass
    p.add_interval("verbose", 0.0, 1.0, level=2)
    names = [iv.name for iv in p.intervals()]
    assert names == ["coarse"]


def test_serialization_restores_level_and_cap():
    """from_dict must carry level/max_intervals through the worker ->
    master profile round-trip: a merged profile that re-filtered or
    re-capped on the master would silently drop spans the worker
    already admitted."""
    p = Profiler(node="w", level=2, max_intervals=7)
    with p.span("detail", level=2):
        pass
    q = Profiler.from_dict(p.to_dict())
    assert q.level == 2
    assert q.max_intervals == 7
    assert [iv.name for iv in q.intervals()] == ["detail"]
    # a restored profile must admit the same levels the source did:
    # level-2 spans survived the wire, so new level-2 recording (e.g.
    # during a master-side merge) must not be filtered either
    q.add_interval("post", 0.0, 1.0, level=2)
    assert {iv.name for iv in q.intervals()} == {"detail", "post"}
    # legacy payloads without the keys must not re-filter or re-cap
    d = p.to_dict()
    del d["level"], d["max_intervals"]
    legacy = Profiler.from_dict(d)
    assert [iv.name for iv in legacy.intervals()] == ["detail"]
    legacy.add_interval("post2", 0.0, 1.0, level=2)
    assert "post2" in {iv.name for iv in legacy.intervals()}


def test_interval_cap_counts_drops():
    p = Profiler(max_intervals=5)
    for i in range(9):
        with p.span(f"s{i}"):
            pass
    assert len(p.intervals()) == 5
    assert p.counters["profiler_dropped"] == 4


def test_profiler_level_knob(sc=None):
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    import scanner_tpu.kernels
    from scanner_tpu import video as scv
    import tempfile, os
    root = tempfile.mkdtemp(prefix="prof_")
    vid = os.path.join(root, "v.mp4")
    scv.synthesize_video(vid, num_frames=16, width=64, height=48, fps=24)
    c = Client(db_path=os.path.join(root, "db"))
    try:
        def run(level, name):
            frame = c.io.Input([NamedVideoStream(c, "t", path=vid)])
            out = NamedStream(c, name)
            jid = c.run(c.io.Output(c.ops.Histogram(frame=frame), [out]),
                        PerfParams.manual(8, 16, profiler_level=level),
                        cache_mode=CacheMode.Overwrite, show_progress=False)
            return c.get_profile(jid).statistics()

        st0 = run(0, "p0")
        st1 = run(1, "p1")
        # level 0: coarse stage spans only; level 1 adds per-op detail
        assert "load" in st0 and "evaluate" in st0 and "save" in st0
        assert "evaluate:Histogram" not in st0
        assert "evaluate:Histogram" in st1
    finally:
        c.stop()


def test_device_trace_merged_at_level2():
    """profiler_level >= 2 captures the XLA device timeline around the
    job and Profile.write_trace merges it with the host stage spans into
    one Chrome-trace JSON (SURVEY §5 tracing row: jax.profiler hooks)."""
    import json
    import os
    import tempfile

    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    import scanner_tpu.kernels  # noqa: F401
    from scanner_tpu import video as scv
    from scanner_tpu.util.jaxprof import DEVICE_PID_BASE

    root = tempfile.mkdtemp(prefix="devtrace_")
    vid = os.path.join(root, "v.mp4")
    scv.synthesize_video(vid, num_frames=16, width=64, height=48, fps=24)
    c = Client(db_path=os.path.join(root, "db"))
    try:
        frame = c.io.Input([NamedVideoStream(c, "t", path=vid)])
        out = NamedStream(c, "p2")
        jid = c.run(c.io.Output(c.ops.Histogram(frame=frame), [out]),
                    PerfParams.manual(8, 16, profiler_level=2),
                    cache_mode=CacheMode.Overwrite, show_progress=False)
        prof = c.get_profile(jid)
        recs = [r for p in prof.profilers
                for r in getattr(p, "device_traces", [])]
        assert recs, "no device trace captured at level 2"
        trace_path = os.path.join(root, "merged.trace.json")
        prof.write_trace(trace_path)
        doc = json.load(open(trace_path))
        evs = doc["traceEvents"]
        host = [e for e in evs if e.get("pid", 0) < DEVICE_PID_BASE
                and e.get("ph") == "X"]
        dev = [e for e in evs if e.get("pid", 0) >= DEVICE_PID_BASE]
        assert any(e["name"] == "load" for e in host)
        assert dev, "device events missing from merged trace"
        # alignment: device events (incl. the Python spans the merge
        # filters by default — on the CPU backend they may be ALL the
        # trace has) sit inside the CAPTURE window [t0, t1] after the t0
        # shift.  The window, not the first host stage span, is the
        # alignment anchor: the level-2 python tracer records thread
        # bootstrap/setup work between start_trace and the first load
        # span, and that gap can be tens of seconds on a slow host.
        from scanner_tpu.util.jaxprof import load_device_events
        full = load_device_events(recs[0], include_python=True)
        dev_ts = [e["ts"] for e in full
                  if "ts" in e and e.get("ph") != "M"]
        t0_us, t1_us = recs[0]["t0"] * 1e6, recs[0]["t1"] * 1e6
        assert dev_ts and min(dev_ts) >= t0_us - 1e6
        assert max(dev_ts) <= t1_us + 60e6
        # and the host stage spans sit inside that same window (one
        # merged perfetto timeline, host and device lanes on one clock:
        # the trace wraps the whole pipeline, so every stage span falls
        # between start_trace and stop_trace)
        host_ts = [e["ts"] for e in host]
        assert min(host_ts) >= t0_us - 1e6
        assert max(host_ts) <= t1_us + 60e6
        # level 1 must NOT capture a device trace
        frame = c.io.Input([NamedVideoStream(c, "t", path=vid)])
        out = NamedStream(c, "p1b")
        jid1 = c.run(c.io.Output(c.ops.Histogram(frame=frame), [out]),
                     PerfParams.manual(8, 16, profiler_level=1),
                     cache_mode=CacheMode.Overwrite, show_progress=False)
        assert not [r for p in c.get_profile(jid1).profilers
                    for r in getattr(p, "device_traces", [])]
    finally:
        c.stop()
