"""Parallel-layer tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scanner_tpu.parallel import (auto_axes, make_mesh, make_ring_attention,
                                  reference_attention, sharded_stencil_map,
                                  shard_batch, temporal_diff)


def test_distributed_shutdown_resets_reinit_latch(monkeypatch):
    """The gang-survivor fix: `_init_config` used to latch once per
    process and any different config raised forever — a member of an
    aborted gang could never rendezvous at a NEW coordinator.
    shutdown() resets the latch (and tears the distributed client
    down); a follow-up initialize with a different config is legal."""
    from scanner_tpu.parallel import distributed as dist

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: calls.append("shutdown"))
    monkeypatch.setattr(jax, "clear_backends",
                        lambda: None, raising=False)
    monkeypatch.setattr(dist, "_init_config", None)
    a = dist.CoordinatorConfig("localhost:1", 2, 0)
    b = dist.CoordinatorConfig("localhost:2", 1, 0)
    dist.initialize(a, init_timeout=7)
    assert dist.is_initialized() and dist.current_config() == a
    # the bounded default: every initialize carries a timeout
    assert calls[-1]["initialization_timeout"] == 7
    # same config: idempotent no-op; different config: loud error that
    # names the fix
    dist.initialize(a)
    with pytest.raises(Exception, match="shutdown"):
        dist.initialize(b)
    dist.shutdown()
    assert "shutdown" in calls and not dist.is_initialized()
    dist.initialize(b)  # the NEW coordinator is now legal
    assert dist.current_config() == b
    # default init timeout is bounded, never unbounded
    assert calls[-1]["initialization_timeout"] \
        == int(dist.DEFAULT_INIT_TIMEOUT_S)
    dist.shutdown()
    assert dist.shutdown() is None  # idempotent


def test_rendezvous_failure_is_transient(monkeypatch):
    """A failed rendezvous raises RendezvousError, which the engine
    classifies TRANSIENT — a lost peer re-forms the gang strike-free
    instead of striking a healthy job."""
    from scanner_tpu.engine.service import _is_transient_failure
    from scanner_tpu.parallel import distributed as dist

    def boom(**kw):
        raise RuntimeError("barrier timed out")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    monkeypatch.setattr(dist, "_init_config", None)
    with pytest.raises(dist.RendezvousError) as ei:
        dist.initialize(dist.CoordinatorConfig("localhost:9", 2, 1),
                        init_timeout=1)
    assert not dist.is_initialized()
    assert _is_transient_failure(ei.value)


def test_mesh_factoring():
    assert len(jax.devices()) == 8
    m = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    assert m.shape == {"dp": 2, "sp": 2, "tp": 2}
    m = make_mesh()  # all devices on dp
    assert m.shape["dp"] == 8
    ax = auto_axes(8)
    assert np.prod(list(ax.values())) == 8
    with pytest.raises(ValueError):
        make_mesh({"dp": 3})


def test_halo_exchange_temporal_diff():
    mesh = make_mesh({"sp": 8, "dp": 1, "tp": 1})
    x = jnp.arange(32.0).reshape(32, 1) ** 1.5
    diff = temporal_diff(mesh, axis="sp")
    got = np.asarray(diff(x))
    expect = np.asarray(x) - np.concatenate([np.asarray(x[:1]),
                                             np.asarray(x[:-1])])
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_sharded_stencil_wide():
    # stencil [-2, 0, 1] across shard boundaries, REPEAT_EDGE at the ends
    mesh = make_mesh({"sp": 4, "dp": 1, "tp": 1})
    x = jnp.arange(16.0).reshape(16, 1)

    def window_sum(padded):
        return padded[:-3] + padded[2:-1] + padded[3:]

    f = sharded_stencil_map(window_sum, stencil=[-2, 0, 1], mesh=mesh,
                            axis="sp")
    got = np.asarray(f(x))
    xs = np.asarray(x)
    expect = np.stack([
        xs[max(i - 2, 0)] + xs[i] + xs[min(i + 1, 15)] for i in range(16)])
    np.testing.assert_allclose(got, expect, rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh({"sp": 4, "dp": 1, "tp": 1})
    rng = np.random.RandomState(0)
    B, T, H, D = 2, 32, 2, 16
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    ring = make_ring_attention(mesh, axis="sp", causal=causal)
    got = np.asarray(ring(q, k, v))
    ref = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_multihost_sharded_train_step():
    """Two OS processes x 4 virtual CPU devices join one jax.distributed
    runtime and execute a dp/sp/tp-sharded train step over the global
    8-device mesh — collectives cross the process boundary (the multi-host
    analogue of the reference's worker-per-node NCCL topology)."""
    from multihost_child import spawn_multihost

    outs = spawn_multihost(n_processes=2, devices_per_process=4,
                           timeout=300)
    losses = [float(o.split("MULTIHOST_LOSS")[1].split()[0]) for o in outs]
    # the loss is a global reduction: every process must agree
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_tiles(causal):
    """block_k < local block: each ring step consumes K/V in multiple
    flash tiles; results stay exact incl. causal masks that cut through
    tile boundaries."""
    mesh = make_mesh({"sp": 2, "dp": 1, "tp": 1})
    rng = np.random.RandomState(5)
    B, T, H, D = 2, 64, 2, 16   # Tl = 32, tiles of 8 -> 4 tiles/step
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    ring = make_ring_attention(mesh, axis="sp", causal=causal, block_k=8)
    got = np.asarray(ring(q, k, v))
    ref = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    # non-divisible request falls back to the largest divisor
    from scanner_tpu.parallel.ring_attention import _flash_block_k
    assert _flash_block_k(32, 24) == 16
    assert _flash_block_k(32, 512) == 32
    assert _flash_block_k(7, 4) == 1


def test_ring_attention_grad():
    mesh = make_mesh({"sp": 4, "dp": 1, "tp": 1})
    rng = np.random.RandomState(1)
    B, T, H, D = 1, 16, 1, 8
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    ring = make_ring_attention(mesh, axis="sp")

    g1 = jax.grad(lambda q: ring(q, k, v).sum())(q)
    g2 = jax.grad(lambda q: reference_attention(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-3,
                               atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    """All-to-all (Ulysses) sequence parallelism is exact: head-sharded
    full attention after one re-shard equals the single-device result,
    and is interchangeable with ring attention (same contract)."""
    from scanner_tpu.parallel import make_ulysses_attention

    mesh = make_mesh({"sp": 4, "dp": 1, "tp": 1})
    rng = np.random.RandomState(1)
    B, T, H, D = 2, 32, 4, 16   # H divisible by sp=4
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    uly = make_ulysses_attention(mesh, axis="sp", causal=causal)
    got = np.asarray(uly(q, k, v))
    ref = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    # drop-in equivalence with the ring path
    ring = make_ring_attention(mesh, axis="sp", causal=causal)
    np.testing.assert_allclose(got, np.asarray(ring(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_attention_gradients():
    """The two all-to-alls differentiate: grads match the reference."""
    from scanner_tpu.parallel import make_ulysses_attention

    mesh = make_mesh({"sp": 2, "dp": 1, "tp": 1})
    rng = np.random.RandomState(2)
    B, T, H, D = 1, 8, 2, 8
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    uly = make_ulysses_attention(mesh, axis="sp")

    g1 = jax.grad(lambda q: (uly(q, k, v) ** 2).sum())(q)
    g2 = jax.grad(
        lambda q: (reference_attention(q, k, v) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    from scanner_tpu.parallel import make_ulysses_attention

    mesh = make_mesh({"sp": 4, "dp": 1, "tp": 1})
    q = jnp.zeros((1, 16, 3, 8), jnp.float32)  # 3 heads, sp=4
    uly = make_ulysses_attention(mesh, axis="sp")
    with pytest.raises(ValueError, match="divisible"):
        uly(q, q, q)


def test_pose_net_with_ulysses_attention():
    """The flagship model accepts Ulysses as its attn_fn — the sp axis
    serves either sequence-parallel scheme without model changes."""
    from scanner_tpu.models import init_params
    from scanner_tpu.parallel import make_ulysses_attention, sharding

    mesh = make_mesh({"sp": 2, "dp": 1, "tp": 1})
    attn = make_ulysses_attention(mesh, axis="sp")
    model, params = init_params(jax.random.PRNGKey(0),
                                clip_shape=(1, 4, 32, 32, 3), width=8,
                                attn_fn=attn)
    clip = jax.device_put(
        np.zeros((2, 4, 32, 32, 3), np.uint8),
        sharding(mesh, None, "sp"))
    out = jax.jit(model.apply)(params, clip)
    assert out.shape == (2, 4, 8, 8, 17)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_pallas_matches_reference(causal):
    """impl='pallas' runs each ring step through the fused flash kernel
    (interpret mode off-TPU); results match the exact reference, incl.
    causal masks crossing ring-block and flash-tile boundaries."""
    mesh = make_mesh({"sp": 4, "dp": 1, "tp": 1})
    rng = np.random.RandomState(7)
    B, T, H, D = 2, 32, 2, 16   # Tl = 8; block_q/k of 4 -> 2x2 tiles/step
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    ring = make_ring_attention(mesh, axis="sp", causal=causal,
                               impl="pallas", block_q=4, block_k=4)
    got = np.asarray(ring(q, k, v))
    ref = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_pallas_grad_matches_xla():
    """The pallas forward carries an XLA-path custom_vjp: gradients are
    available and identical to the XLA ring (which matches reference)."""
    mesh = make_mesh({"sp": 2, "dp": 1, "tp": 1})
    rng = np.random.RandomState(8)
    B, T, H, D = 1, 16, 1, 8
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    pal = make_ring_attention(mesh, axis="sp", impl="pallas")
    xla = make_ring_attention(mesh, axis="sp")
    gp = jax.grad(lambda q: pal(q, k, v).sum())(q)
    gx = jax.grad(lambda q: xla(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.slow
def test_multihost_4proc_train_step():
    """Four OS processes x 2 virtual devices — the process count of a
    small pod slice.  The global mesh spans all four; every rank must
    agree on the globally-reduced loss."""
    from multihost_child import spawn_multihost

    # 600s: the deadline bounds the WHOLE launch and 4 concurrent jax
    # imports + compiles share one core when the full suite runs
    outs = spawn_multihost(n_processes=4, devices_per_process=2,
                           timeout=600)
    losses = [float(o.split("MULTIHOST_LOSS")[1].split()[0]) for o in outs]
    for l in losses[1:]:
        assert l == pytest.approx(losses[0], rel=1e-6)


@pytest.mark.slow
def test_multihost_failure_then_restart():
    """A rank dying mid-job must fail the group (never a silent wrong
    result), and a FRESH group must be startable on the same coordinator
    port afterwards — the restart path an elastic cluster manager
    (deploy.py StatefulSets) relies on.  spawn_multihost verifies the
    crash rank really joined then exit(1)d, and that no surviving rank
    completes successfully, before raising."""
    from multihost_child import free_port, spawn_multihost

    port = free_port()
    with pytest.raises(RuntimeError,
                       match="rank death confirmed"):
        spawn_multihost(n_processes=2, devices_per_process=2, timeout=120,
                        crash_rank=1, port=port)
    # same port, fresh group: must come up and agree
    outs = spawn_multihost(n_processes=2, devices_per_process=2,
                           timeout=300, port=port)
    losses = [float(o.split("MULTIHOST_LOSS")[1].split()[0]) for o in outs]
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)


@pytest.mark.parametrize("dp,S,M,B", [
    (2, 4, 4, 8),   # canonical: 2-way dp, 4 stages, 4 microbatches
    (1, 2, 1, 4),   # single microbatch: schedule is all bubbles but two
    (1, 8, 3, 6),   # deep pipeline, microbatches not a power of two
])
def test_pipeline_matches_sequential(dp, S, M, B):
    """The GPipe microbatch schedule (parallel/pp.py) is semantically the
    sequential stage composition: forward AND gradients agree with the
    unpipelined loop to f32 precision (bubble steps are masked, so their
    cotangents vanish) — across schedule shapes."""
    import jax.numpy as jnp
    from scanner_tpu.parallel import (make_mesh, make_pipeline,
                                      stack_stage_params)

    mesh = make_mesh({"dp": dp, "sp": 1, "tp": 1, "pp": S})
    T, C = 6, 16
    rng = np.random.RandomState(0)
    stage_params = [{"w": rng.randn(C, C).astype(np.float32) * 0.1,
                     "b": rng.randn(C).astype(np.float32) * 0.1}
                    for _ in range(S)]
    stacked = stack_stage_params(stage_params)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    pipe = make_pipeline(mesh, stage_fn, num_microbatches=M)
    x = rng.randn(B, T, C).astype(np.float32)

    got = np.asarray(jax.jit(pipe)(stacked, x))
    want = x
    for p in stage_params:
        want = np.tanh(want @ p["w"] + p["b"])
    np.testing.assert_allclose(got, want, atol=1e-6)

    def loss_pipe(sp):
        return jnp.sum(pipe(sp, x) ** 2)

    def loss_seq(sp):
        h = jnp.asarray(x)
        for i in range(S):
            p = jax.tree_util.tree_map(lambda a, i=i: a[i], sp)
            h = stage_fn(p, h)
        return jnp.sum(h ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_seq = jax.jit(jax.grad(loss_seq))(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g_pipe,
        g_seq)


def test_pipeline_rejects_indivisible_microbatch():
    import jax.numpy as jnp
    from scanner_tpu.parallel import (make_mesh, make_pipeline,
                                      stack_stage_params)

    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1, "pp": 2})
    C = 8
    stacked = stack_stage_params(
        [{"w": np.eye(C, dtype=np.float32)} for _ in range(2)])
    pipe = make_pipeline(mesh, lambda p, x: x @ p["w"],
                         num_microbatches=3)
    with pytest.raises(ValueError, match="not divisible"):
        pipe(stacked, np.zeros((4, 2, C), np.float32))


@pytest.mark.slow
def test_pp_train_step_full_model():
    """make_sharded_train_step on a dp x tp x pp mesh pipelines the
    temporal trunk (each pp rank holds one stage's weights) and still
    optimizes; pp > 1 with sp > 1 is rejected (stages are
    collective-free)."""
    from scanner_tpu.models import make_sharded_train_step
    from scanner_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2, "pp": 2})
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(4, 4, 64, 64, 3), width=16)
    params, opt_state, l1 = step(params, opt_state, clip, target)
    params, opt_state, l2 = step(params, opt_state, clip, target)
    assert np.isfinite(float(l1)) and float(l2) < float(l1)

    with pytest.raises(ValueError, match="pp > 1 requires sp == 1"):
        make_sharded_train_step(make_mesh({"dp": 1, "sp": 2, "tp": 2,
                                           "pp": 2}),
                                clip_shape=(4, 4, 64, 64, 3), width=16)

    # the pipelined trunk depth IS the pp size; an explicit mismatching
    # temporal_layers must raise, not silently reshape the architecture
    with pytest.raises(ValueError, match="temporal_layers=3"):
        make_sharded_train_step(make_mesh({"dp": 2, "sp": 1, "tp": 2,
                                           "pp": 2}),
                                clip_shape=(4, 4, 64, 64, 3), width=16,
                                temporal_layers=3)


def test_pipeline_rejects_stage_count_mismatch():
    """A stacked stage count that differs from the pp axis size must be a
    loud error — running only every (S_stack/S_mesh)-th stage would be a
    silently wrong model."""
    import jax.numpy as jnp
    from scanner_tpu.parallel import (make_mesh, make_pipeline,
                                      stack_stage_params)

    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1, "pp": 2})
    C = 8
    stacked = stack_stage_params(
        [{"w": np.eye(C, dtype=np.float32)} for _ in range(4)])
    pipe = make_pipeline(mesh, lambda p, x: x @ p["w"],
                         num_microbatches=2)
    with pytest.raises(ValueError, match="must match"):
        pipe(stacked, np.zeros((4, 2, C), np.float32))


@pytest.mark.slow
def test_ep_axis_train_step():
    """A dedicated 'ep' mesh axis shards MoE expert tensors (instead of
    folding experts onto 'tp') and the sharded train step still
    optimizes; the expert leaves actually carry the 'ep' sharding."""
    from scanner_tpu.models import make_sharded_train_step
    from scanner_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 1, "ep": 2})
    assert mesh.axis_names == ("dp", "sp", "tp", "ep")
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(4, 8, 64, 64, 3), width=16)
    expert_leaves = [
        (path, x) for path, x in
        jax.tree_util.tree_flatten_with_path(params)[0]
        if any(getattr(p, "key", None) in ("w1", "w2") for p in path)]
    assert expert_leaves, "MoE expert tensors not found in params"
    for _path, x in expert_leaves:
        assert "ep" in str(x.sharding.spec), x.sharding
    params, opt_state, l1 = step(params, opt_state, clip, target)
    params, opt_state, l2 = step(params, opt_state, clip, target)
    assert np.isfinite(float(l1)) and float(l2) < float(l1)


@pytest.mark.slow
def test_pp_and_ep_axes_coexist():
    """A mesh carrying BOTH optional axes (pp pipeline stages + ep
    experts) compiles and optimizes: stacked stage weights take the
    'pp' sharding (experts inside a stage ride along), and the 'ep'
    axis idles harmlessly for the pipelined trunk while remaining
    available to non-pipelined parts."""
    from scanner_tpu.models import make_sharded_train_step
    from scanner_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1, "pp": 2, "ep": 2})
    assert mesh.axis_names == ("dp", "sp", "tp", "pp", "ep")
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(4, 4, 64, 64, 3), width=16)
    params, opt_state, l1 = step(params, opt_state, clip, target)
    params, opt_state, l2 = step(params, opt_state, clip, target)
    assert np.isfinite(float(l1)) and float(l2) < float(l1)
