"""Child process for tests/test_coststats.py: golden-pipeline warm-up
compile ledger on a virtual multi-device host.

Spawned with cpu_only_env(n_devices=2) + SCANNER_TPU_KERNEL_DEVICES=all
+ SCANNER_TPU_PRECOMPILE=1 so evaluator affinity assigns a chip per
pipeline instance and the bucket-ladder warm-up device_puts example
args — every warm-up rung then really compiles per chip, exactly like a
multi-chip TPU worker, and the compile ledger must account for each
(op, device, bucket).  Usage:

    python coststats_runner.py <video_path> <out_json>
"""

import json
import os
import sys
import tempfile


def main() -> int:
    video, out_path = sys.argv[1], sys.argv[2]
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    import scanner_tpu.kernels  # noqa: F401  (registers Histogram)
    from scanner_tpu.util import coststats
    import jax

    root = tempfile.mkdtemp(prefix="cseff_")
    sc = Client(db_path=os.path.join(root, "db"))
    sc.ingest_videos([("cs", video)])

    frame = sc.io.Input([NamedVideoStream(sc, "cs")])
    out = NamedStream(sc, "cs_hist")
    # wp=8 -> Histogram's warm ladder is bucket_ladder(8) = [4, 8]
    sc.run(sc.io.Output(sc.ops.Histogram(frame=frame), [out]),
           PerfParams.manual(8, 16), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    rows = list(out.load())

    results = {
        "n_devices": len(jax.local_devices()),
        "n_rows": len(rows),
        "ledger": coststats.compile_ledger(),
        "summary": coststats.ledger_summary(),
        "op_efficiency": coststats.op_efficiency(),
        "report": sc.compile_report(),
    }
    sc.stop()
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1, default=str)
    print("COSTSTATS_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
