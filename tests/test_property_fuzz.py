"""Randomized property test of exact-row scheduling.

SURVEY §7 names the stencil x sampler x state row derivation the
hardest part of the rebuild ("must be property-tested").  The example
suite (test_engine.py / test_graph_analysis.py) pins known cases; this
fuzz runs RANDOM transform chains through the real engine at random
packet geometries and compares every output row against a pure-Python
semantic oracle — composition bugs (a sampler stacked on a stencil on a
state op at an unlucky task boundary) have nowhere to hide.
"""

import random
import struct
from typing import List, Optional, Sequence

import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         NullElement, PerfParams, register_op)

N_SEEDS = 12


def pack(v: int) -> bytes:
    return struct.pack("<q", v)


def unpack(b: bytes) -> int:
    return struct.unpack("<q", b)[0]


@register_op(name="_FzStencilSum", stencil=[-1, 0, 1])
class _FzStencilSum(Kernel):
    """out[i] = in[i-1] + in[i] + in[i+1] (REPEAT_EDGE at bounds)."""

    def execute(self, x: Sequence[bytes]) -> bytes:
        return pack(sum(unpack(b) for b in x))


@register_op(name="_FzCumSum", unbounded_state=True)
class _FzCumSum(Kernel):
    """out[i] = sum(in[0..i]) — unbounded state, prefix recomputed per
    task with reset at discontinuities."""

    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.acc = 0

    def execute(self, x: bytes) -> bytes:
        self.acc += unpack(x)
        return pack(self.acc)


# oracle: each step maps the full upstream value list (ints or None for
# null rows) to the downstream list, mirroring engine semantics
def _clamp(i, n):
    return max(0, min(n - 1, i))


def o_stencil(vals):
    n = len(vals)
    out = []
    for i in range(n):
        win = [vals[_clamp(i + k, n)] for k in (-1, 0, 1)]
        out.append(None if any(v is None for v in win) else sum(win))
    return out


def o_cumsum(vals):
    acc, out = 0, []
    for v in vals:
        assert v is not None
        acc += v
        out.append(acc)
    return out


def gen_chain(rng: random.Random, n0: int):
    """Random transform chain: list of (kind, arg) + oracle values."""
    vals: List[Optional[int]] = list(range(100, 100 + n0))
    steps = []
    n_ops = 0
    has_null = False
    for _ in range(rng.randint(2, 4)):
        n = len(vals)
        choices = ["stride", "range", "strided_range", "gather", "repeat"]
        if not has_null:
            choices += ["repeat_null"]
        if n_ops < 2:
            # stencil after RepeatNull exercises null-window propagation;
            # only the STATE op is undefined over null rows
            choices += ["stencil", "stencil"]
            if n >= 2 and not has_null:
                choices += ["cumsum"]
        kind = rng.choice(choices)
        if kind == "stride":
            s = rng.randint(2, 4)
            steps.append(("stride", s))
            vals = vals[::s]
        elif kind == "range":
            a = rng.randint(0, n - 1)
            b = rng.randint(a + 1, n)
            steps.append(("range", (a, b)))
            vals = vals[a:b]
        elif kind == "strided_range":
            a = rng.randint(0, n - 1)
            b = rng.randint(a + 1, n)
            s = rng.randint(2, 3)
            steps.append(("strided_range", (a, b, s)))
            vals = vals[a:b:s]
        elif kind == "gather":
            k = rng.randint(1, n)
            rows = sorted(rng.sample(range(n), k))
            steps.append(("gather", rows))
            vals = [vals[r] for r in rows]
        elif kind == "repeat":
            k = rng.randint(2, 3)
            steps.append(("repeat", k))
            vals = [v for v in vals for _ in range(k)]
        elif kind == "repeat_null":
            k = rng.randint(2, 3)
            steps.append(("repeat_null", k))
            out: List[Optional[int]] = []
            for v in vals:
                out.append(v)
                out.extend([None] * (k - 1))
            vals = out
            has_null = True
        elif kind == "stencil":
            steps.append(("stencil", None))
            vals = o_stencil(vals)
            n_ops += 1
        elif kind == "cumsum":
            steps.append(("cumsum", None))
            vals = o_cumsum(vals)
            n_ops += 1
    return steps, vals


def apply_steps(sc, col, steps):
    for kind, arg in steps:
        if kind == "stride":
            col = sc.streams.Stride(col, [{"stride": arg}])
        elif kind == "range":
            col = sc.streams.Range(col, [arg])
        elif kind == "strided_range":
            col = sc.streams.StridedRange(col, [arg])
        elif kind == "gather":
            col = sc.streams.Gather(col, [arg])
        elif kind == "repeat":
            col = sc.streams.Repeat(col, [arg])
        elif kind == "repeat_null":
            col = sc.streams.RepeatNull(col, [arg])
        elif kind == "stencil":
            col = sc.ops._FzStencilSum(x=col)
        elif kind == "cumsum":
            col = sc.ops._FzCumSum(x=col)
    return col


def build_graph(sc, src_stream, steps):
    return apply_steps(sc, sc.io.Input([src_stream]), steps)


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_random_chain_matches_oracle(tmp_path, seed):
    rng = random.Random(1000 + seed)
    n0 = rng.randint(24, 60)
    steps, expect = gen_chain(rng, n0)
    w = rng.choice([1, 2, 3, 5])
    io = w * rng.randint(1, 6)

    sc = Client(db_path=str(tmp_path / "db"))
    try:
        sc.new_table("src", ["output"],
                     [[pack(100 + i)] for i in range(n0)])
        src = NamedStream(sc, "src")
        out = NamedStream(sc, "out")
        sc.run(sc.io.Output(build_graph(sc, src, steps), [out]),
               PerfParams.manual(w, io), cache_mode=CacheMode.Overwrite,
               show_progress=False)
        got = [None if isinstance(r, NullElement) else unpack(r)
               for r in out.load()]
        assert got == expect, (
            f"seed {seed}: chain {steps} w={w} io={io}\n"
            f"got    {got}\nexpect {expect}")
    finally:
        sc.stop()


def gen_inner(rng: random.Random, groups: List[List[int]]):
    """Random transforms INSIDE a slice: applied independently per group
    (state resets, stencils clamp at group bounds).  Returns (steps,
    per-group oracle outputs)."""
    steps = []
    n_ops = 0
    for _ in range(rng.randint(1, 2)):
        kind = rng.choice((["stencil", "cumsum"] if n_ops < 1 else [])
                          + ["repeat"])
        if kind == "stencil":
            steps.append(("stencil", None))
            groups = [o_stencil(g) for g in groups]
            n_ops += 1
        elif kind == "cumsum":
            steps.append(("cumsum", None))
            groups = [o_cumsum(g) for g in groups]
            n_ops += 1
        elif kind == "repeat":
            k = rng.randint(2, 3)
            steps.append(("repeat", k))
            groups = [[v for v in g for _ in range(k)] for g in groups]
    return steps, groups


@pytest.mark.parametrize("seed", range(8))
def test_random_slice_chain_matches_oracle(tmp_path, seed):
    """Slice -> random per-group transforms -> Unslice: group boundaries
    must behave as stream boundaries (stencil REPEAT_EDGE clamps at the
    group edge, unbounded state resets per group), and Unslice must
    stitch group outputs back in order — at random packet geometries."""
    rng = random.Random(7000 + seed)
    n0 = rng.randint(24, 48)
    vals = list(range(100, 100 + n0))
    # random contiguous partition of [0, n0) into 2-4 groups
    n_groups = rng.randint(2, 4)
    cuts = sorted(rng.sample(range(1, n0), n_groups - 1))
    bounds = [0] + cuts + [n0]
    intervals = [(bounds[i], bounds[i + 1]) for i in range(n_groups)]
    groups = [vals[a:b] for a, b in intervals]
    steps, groups = gen_inner(rng, groups)
    expect = [v for g in groups for v in g]
    w = rng.choice([1, 2, 3])
    io = w * rng.randint(1, 5)

    sc = Client(db_path=str(tmp_path / "db"))
    try:
        sc.new_table("src", ["output"],
                     [[pack(100 + i)] for i in range(n0)])
        col = sc.io.Input([NamedStream(sc, "src")])
        col = sc.streams.Slice(col, partitions=[
            sc.partitioner.strided_ranges(intervals, 1)])
        col = apply_steps(sc, col, steps)
        col = sc.streams.Unslice(col)
        out = NamedStream(sc, "out")
        sc.run(sc.io.Output(col, [out]), PerfParams.manual(w, io),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        got = [unpack(r) for r in out.load()]
        assert got == expect, (
            f"seed {seed}: intervals {intervals} steps {steps} "
            f"w={w} io={io}\ngot    {got}\nexpect {expect}")
    finally:
        sc.stop()
