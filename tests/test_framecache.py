"""Paged per-device HBM frame cache (engine/framecache.py + wiring).

Covers page math (keyframe-aligned auto sizing, fill-buffer completion,
fixed-size pages with a ragged tail), LRU eviction order,
eviction-under-pinning, the hbm_pressure -> capacity-shrink actuation
seed, and the correctness story: bit-exact equivalence cache-on vs
cache-off for stencil-overlap, Gather, null-interleaved, and multi-chip
pipelines (pages are per-device — chip 1 must never gather chip 0's
pages), plus the memory.pressure chaos path with the cache armed.
"""

import gc
import json
import urllib.request

import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
from scanner_tpu.common import NullElement
from scanner_tpu.engine import framecache as fc
from scanner_tpu.util import faults
from scanner_tpu.util import metrics as _mx

N_FRAMES = 48


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    return sum(s["value"] for s in entry.get("samples", [])
               if all(s["labels"].get(k) == v for k, v in labels.items()))


@pytest.fixture(autouse=True)
def _cache_state():
    """Isolate global frame-cache knobs/state per test (the pool is a
    process singleton keyed by (db, table), but tests share tmp dirs
    slowly enough that stale pages could still pin memory)."""
    import scanner_tpu.engine.framecache as mod
    saved = (mod._ENABLED, mod._capacity_mb, mod._page_frames_cfg)
    yield
    mod._ENABLED, mod._capacity_mb, mod._page_frames_cfg = saved
    if mod._CACHE is not None:
        mod._CACHE.clear()
    faults.clear()


# ---------------------------------------------------------------------------
# page-math units (private FrameCache instances; no engine involved)
# ---------------------------------------------------------------------------

def _mkplan(c, rows, total=64, keyint=0, table=("db", 1), fmt="rgb24",
            item=0):
    return c.plan(None, table, "frame", item, fmt,
                  np.asarray(rows, np.int64), total_rows=total,
                  keyint=keyint)


def _rowdata(rows, shape=(2, 2, 3)):
    out = np.zeros((len(rows),) + shape, np.uint8)
    for i, r in enumerate(rows):
        out[i].fill(r % 251)
    return out


def test_auto_page_size_is_keyint_aligned():
    c = fc.FrameCache()
    p = _mkplan(c, [0], keyint=12)
    assert p.page_frames == 36  # smallest 12-multiple >= 32
    p2 = c.plan(None, ("db", 2), "frame", 0, "rgb24",
                np.asarray([0]), total_rows=64, keyint=32)
    assert p2.page_frames == 32
    p3 = c.plan(None, ("db", 3), "frame", 0, "rgb24",
                np.asarray([0]), total_rows=64, keyint=0)
    assert p3.page_frames == 32
    fc.set_page_frames(8)
    p4 = c.plan(None, ("db", 4), "frame", 0, "rgb24",
                np.asarray([0]), total_rows=64, keyint=12)
    assert p4.page_frames == 8  # explicit config wins over auto


def test_fill_assemble_roundtrip_and_second_plan_hits():
    fc.set_page_frames(4)
    c = fc.FrameCache()
    rows = np.arange(8)
    p = _mkplan(c, rows, total=10)
    assert len(p.miss_rows) == 8 and not p.hit_mask.any()
    data = _rowdata(rows)
    out = np.asarray(c.assemble(p, p.miss_rows, data))
    assert np.array_equal(out, data)
    p.lease.release()
    # second consultation: both pages resident, bit-exact gather
    p2 = _mkplan(c, [1, 2, 5, 7], total=10)
    assert p2.hit_mask.all() and len(p2.miss_rows) == 0
    out2 = np.asarray(c.assemble(p2, np.zeros(0, np.int64),
                                 np.zeros((0, 1), np.uint8)))
    assert np.array_equal(out2, _rowdata([1, 2, 5, 7]))
    p2.lease.release()
    st = c.status_dict()["devices"]["default"]
    assert st["pages"] == 2 and st["hits"] == 4 and st["misses"] == 8


def test_partial_offers_complete_pages_across_tasks():
    """Fill buffers persist across plans: two tasks each decode half a
    page; the page becomes resident when the second half arrives (the
    cross-task stencil-overlap mechanism)."""
    fc.set_page_frames(8)
    c = fc.FrameCache()
    p1 = _mkplan(c, np.arange(0, 4), total=16)
    c.assemble(p1, p1.miss_rows, _rowdata(range(4)))
    assert c.status_dict()["devices"].get("default", {}).get("pages",
                                                             0) == 0
    p2 = _mkplan(c, np.arange(4, 8), total=16)
    c.assemble(p2, p2.miss_rows, _rowdata(range(4, 8)))
    assert c.status_dict()["devices"]["default"]["pages"] == 1
    p3 = _mkplan(c, np.arange(8), total=16)
    assert p3.hit_mask.all()
    out = np.asarray(c.assemble(p3, np.zeros(0, np.int64),
                                np.zeros((0, 1), np.uint8)))
    assert np.array_equal(out, _rowdata(range(8)))
    for p in (p1, p2, p3):
        p.lease.release()


def test_tail_page_is_short_and_hits():
    fc.set_page_frames(8)
    c = fc.FrameCache()
    rows = np.arange(8, 13)  # tail page [8, 13) of a 13-row item
    p = _mkplan(c, rows, total=13)
    c.assemble(p, p.miss_rows, _rowdata(rows))
    p.lease.release()
    p2 = _mkplan(c, [12], total=13)
    assert p2.hit_mask.all()
    p2.lease.release()
    # a row past the item end never hits (and never crashes)
    st = c.status_dict()["devices"]["default"]
    assert st["pages"] == 1


def test_lru_eviction_order():
    fc.set_page_frames(4)
    c = fc.FrameCache()
    page_bytes = 4 * 2 * 2 * 3
    c._target["default"] = page_bytes * 2  # room for exactly 2 pages
    for base in (0, 4, 8):
        p = _mkplan(c, np.arange(base, base + 4), total=16)
        c.assemble(p, p.miss_rows, _rowdata(range(base, base + 4)))
        p.lease.release()
    st = c.status_dict()["devices"]["default"]
    assert st["pages"] == 2 and st["evictions"] == 1
    # page 0 (oldest, untouched) was the victim; 4.. and 8.. survive
    p = _mkplan(c, np.arange(0, 12), total=16)
    assert not p.hit_mask[:4].any() and p.hit_mask[4:].all()
    p.lease.release()
    # touching page 1 (rows 4..7) then inserting another evicts page 2
    p_touch = _mkplan(c, np.arange(4, 8), total=16)
    p_touch.lease.release()
    p_new = _mkplan(c, np.arange(12, 16), total=16)
    c.assemble(p_new, p_new.miss_rows, _rowdata(range(12, 16)))
    p_new.lease.release()
    p_chk = _mkplan(c, np.arange(4, 12), total=16)
    assert p_chk.hit_mask[:4].all() and not p_chk.hit_mask[4:].any()
    p_chk.lease.release()


def test_eviction_skips_pinned_pages():
    fc.set_page_frames(4)
    c = fc.FrameCache()
    page_bytes = 4 * 2 * 2 * 3
    c._target["default"] = page_bytes  # room for exactly 1 page
    p1 = _mkplan(c, np.arange(4), total=16)
    c.assemble(p1, p1.miss_rows, _rowdata(range(4)))
    # p1's lease still pins page 0: inserting page 1 must NOT evict it
    # (transient overshoot instead)
    p2 = _mkplan(c, np.arange(4, 8), total=16)
    c.assemble(p2, p2.miss_rows, _rowdata(range(4, 8)))
    chk = _mkplan(c, np.arange(4), total=16)
    assert chk.hit_mask.all(), "pinned page was evicted"
    chk.lease.release()
    st = c.status_dict()["devices"]["default"]
    assert st["pinned_bytes"] > 0
    # releasing the pins lets the next insert evict down to capacity
    p1.lease.release()
    p2.lease.release()
    p1.lease.release()  # idempotent
    assert c.status_dict()["devices"]["default"]["pinned_bytes"] == 0
    p3 = _mkplan(c, np.arange(8, 12), total=16)
    c.assemble(p3, p3.miss_rows, _rowdata(range(8, 12)))
    p3.lease.release()
    assert c.status_dict()["devices"]["default"]["live_bytes"] \
        <= page_bytes


def test_pressure_shrink_targets_half_occupancy_and_evicts():
    fc.set_page_frames(4)
    c = fc.FrameCache()
    for base in range(0, 16, 4):
        p = _mkplan(c, np.arange(base, base + 4), total=16)
        c.assemble(p, p.miss_rows, _rowdata(range(base, base + 4)))
        p.lease.release()
    before = _counter("scanner_tpu_framecache_pressure_shrinks_total",
                      device="default")
    c.pressure_shrink("default")
    st = c.status_dict()["devices"]["default"]
    assert st["capacity_bytes"] == fc.MIN_CAPACITY_BYTES
    assert st["pressure_shrinks"] == 1
    assert _counter("scanner_tpu_framecache_pressure_shrinks_total",
                    device="default") == before + 1
    # tiny pages fit far under the floor: nothing evicted here, but a
    # sub-floor target with oversized live bytes must evict
    c._live["default"] = fc.MIN_CAPACITY_BYTES * 4
    c._target["default"] = fc.MIN_CAPACITY_BYTES * 4
    c.pressure_shrink("default")
    assert c.status_dict()["devices"]["default"]["capacity_bytes"] \
        == fc.MIN_CAPACITY_BYTES * 2


def test_fill_fragments_bill_capacity_and_evict_first():
    """Incomplete-page fill fragments are HBM too: they count against
    the capacity target and are the first eviction victims — a sparse
    workload can never hold unbounded invisible device memory."""
    fc.set_page_frames(8)
    c = fc.FrameCache()
    page_bytes = 8 * 2 * 2 * 3
    c._target["default"] = page_bytes  # tight target
    # partial offers across many pages: none completes, all fragments
    for base in range(0, 64, 8):
        p = _mkplan(c, np.arange(base, base + 4), total=64)
        c.assemble(p, p.miss_rows, _rowdata(range(base, base + 4)))
        p.lease.release()
    st = c.status_dict()["devices"]["default"]
    assert st["fill_bytes"] <= page_bytes, st
    assert st["live_bytes"] + st["fill_bytes"] <= page_bytes, st
    # a complete page then displaces remaining fragments, not itself
    p = _mkplan(c, np.arange(0, 8), total=64)
    c.assemble(p, p.miss_rows, _rowdata(range(8)))
    p.lease.release()
    st = c.status_dict()["devices"]["default"]
    assert st["pages"] == 1 and st["fill_bytes"] == 0, st


def test_pressure_shrink_redirects_to_default_pool():
    """Single-chip / affinity-off pools key pages under "default" while
    the hbm_pressure alert names the real chip: the shrink must reach
    the pages that actually exist."""
    fc.set_page_frames(4)
    c = fc.FrameCache()
    for base in (0, 4):
        p = _mkplan(c, np.arange(base, base + 4), total=8)
        c.assemble(p, p.miss_rows, _rowdata(range(base, base + 4)))
        p.lease.release()
    assert c.status_dict()["devices"]["default"]["pages"] == 2
    c.pressure_shrink("tpu:0")  # the alert's label, not the pool's
    st = c.status_dict()["devices"]["default"]
    assert st["pressure_shrinks"] == 1
    assert st["capacity_bytes"] == fc.MIN_CAPACITY_BYTES


def test_hbm_pressure_transition_actuates_via_health_listener():
    """The alerts->actuation seam: a synthetic hbm_pressure firing
    transition delivered through HealthEngine listeners reaches the
    frame cache's shrink hook."""
    calls = []
    orig = fc.FrameCache.pressure_shrink
    fc.cache()  # ensure the listener is registered
    try:
        fc.FrameCache.pressure_shrink = \
            lambda self, dev: calls.append(dev) or 0
        fc._on_alert({"rule": "hbm_pressure", "state": "firing",
                      "labels": {"device": "tpu:3"}})
        fc._on_alert({"rule": "hbm_pressure", "state": "resolved",
                      "labels": {"device": "tpu:3"}})
        fc._on_alert({"rule": "recompile_storm", "state": "firing",
                      "labels": {}})
        assert calls == ["tpu:3"]
        # and through a real engine tick: a private engine with the
        # listener attached delivers transitions the same way
        from scanner_tpu.util.health import AlertRule, HealthEngine
        from scanner_tpu.util.metrics import MetricsRegistry
        reg = MetricsRegistry()
        g = reg.gauge("scanner_tpu_device_hbm_bytes_in_use", "h",
                      labels=["device"])
        lim = reg.gauge("scanner_tpu_device_hbm_limit_bytes", "h",
                        labels=["device"])
        g.labels(device="tpu:7").set(95.0)
        lim.labels(device="tpu:7").set(100.0)
        eng = HealthEngine(reg, rules=[AlertRule(
            name="hbm_pressure",
            series="scanner_tpu_device_hbm_bytes_in_use",
            ratio_to="scanner_tpu_device_hbm_limit_bytes",
            form="value", op=">", value=0.92, for_seconds=0.0,
            severity="critical", by=("device",))], interval=0.1)
        eng.add_listener(fc._on_alert)
        eng.tick(now=1000.0)
        assert calls == ["tpu:3", "tpu:7"]
    finally:
        fc.FrameCache.pressure_shrink = orig


# ---------------------------------------------------------------------------
# end-to-end equivalence (virtual multi-device host; device staging on)
# ---------------------------------------------------------------------------

@pytest.fixture()
def sc(tmp_path, monkeypatch):
    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    from scanner_tpu import video as scv
    import scanner_tpu.kernels  # noqa: F401

    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=8)
    client = Client(db_path=str(tmp_path / "db"))
    client.ingest_videos([("fcvid", vid)])
    yield client
    client.stop()


def _run(sc, name, build, wp=4, io=8, **kw):
    frames = sc.io.Input([NamedVideoStream(sc, "fcvid")])
    out = NamedStream(sc, name)
    sc.run(sc.io.Output(build(sc, frames), [out]),
           PerfParams.manual(wp, io, **kw),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return list(out.load())


def _ab(sc, build, tag, **kw):
    """cache-on twice (cold + warm) vs cache-off; all three bit-exact;
    returns (cold, warm) framecache hit deltas."""
    fc.set_enabled(True)
    h0 = _counter("scanner_tpu_framecache_hits_total")
    on_cold = _run(sc, f"{tag}_on1", build, **kw)
    h1 = _counter("scanner_tpu_framecache_hits_total")
    on_warm = _run(sc, f"{tag}_on2", build, **kw)
    h2 = _counter("scanner_tpu_framecache_hits_total")
    fc.set_enabled(False)
    off = _run(sc, f"{tag}_off", build, **kw)
    assert len(on_cold) == len(on_warm) == len(off)
    for a, b, c in zip(on_cold, on_warm, off):
        if isinstance(c, NullElement):
            assert isinstance(a, NullElement) \
                and isinstance(b, NullElement)
        else:
            assert np.array_equal(np.asarray(a), np.asarray(c))
            assert np.array_equal(np.asarray(b), np.asarray(c))
    return h1 - h0, h2 - h1


def test_stencil_overlap_bit_exact_and_warm_hits(sc, monkeypatch):
    fc.set_page_frames(4)
    # serialize the pipeline: tasks then plan strictly in order, so
    # in-run stencil back-reach reuse is deterministic (with parallel
    # loaders a later task may plan before an earlier task's pages
    # land — reuse still happens, just not countably; the threaded
    # paths are exercised by the other e2e tests)
    monkeypatch.setenv("SCANNER_TPU_NO_PIPELINING", "1")
    # OpticalFlow declares stencil=[-1, 0]: each task's first window
    # reaches one row back into its predecessor's range
    cold, warm = _ab(
        sc, lambda s, f: s.ops.OpticalFlow(frame=f),
        "sten", pipeline_instances_per_node=1)
    # warm run: every frame serves from pages — full reuse
    assert warm >= N_FRAMES
    # cold run: each task's stencil back-reach row (8k-1) hits the
    # page its predecessor completed — in-run cross-task reuse
    assert cold >= (N_FRAMES // 8) - 1


def test_gather_hits_hot_pages_bit_exact(sc):
    fc.set_page_frames(4)

    def dense(s, f):
        return s.ops.Histogram(frame=f)

    def gather(s, f):
        sampled = s.streams.Gather(f, [[0, 3, 9, 17, 18, 33, 47]])
        return s.ops.Histogram(frame=sampled)

    fc.set_enabled(True)
    # one instance: the gather task must land on the chip whose pages
    # the dense run filled
    _run(sc, "g_dense", dense, pipeline_instances_per_node=1)
    h0 = _counter("scanner_tpu_framecache_hits_total")
    on = _run(sc, "g_on", gather, pipeline_instances_per_node=1)
    hits = _counter("scanner_tpu_framecache_hits_total") - h0
    fc.set_enabled(False)
    off = _run(sc, "g_off", gather, pipeline_instances_per_node=1)
    assert len(on) == len(off) == 7
    assert all(np.array_equal(a, b) for a, b in zip(on, off))
    assert hits == 7  # every sampled frame rode the hot pages


def test_null_interleaved_bit_exact(sc):
    def build(s, f):
        ranged = s.streams.Range(f, [(0, 16)])
        spaced = s.streams.RepeatNull(ranged, [2])
        return s.ops.Histogram(frame=spaced)

    # small pages: only rows 0..15 ever decode, so auto(keyint) pages
    # spanning the whole clip would never complete
    fc.set_page_frames(4)
    cold, warm = _ab(sc, build, "nulls", wp=4, io=8,
                     pipeline_instances_per_node=1)
    assert warm >= 16


def test_multichip_pages_are_per_device(sc):
    """Pages are keyed per device: with 2 device-affine instances the
    pool holds distinct per-chip pages, outputs stay bit-exact, and no
    assembly ever mixes chips (jax would raise on a cross-device
    concatenate inside one batch — bit-exactness plus per-device page
    accounting proves isolation)."""
    fc.set_page_frames(4)
    fc.set_enabled(True)
    a = _run(sc, "mc_a",
             lambda s, f: s.ops.Histogram(frame=f),
             pipeline_instances_per_node=2)
    b = _run(sc, "mc_b",
             lambda s, f: s.ops.Histogram(frame=f),
             pipeline_instances_per_node=2)
    fc.set_enabled(False)
    off = _run(sc, "mc_off",
               lambda s, f: s.ops.Histogram(frame=f),
               pipeline_instances_per_node=2)
    assert all(np.array_equal(x, y) for x, y in zip(a, off))
    assert all(np.array_equal(x, y) for x, y in zip(b, off))
    devs = fc.cache().status_dict()["devices"]
    chip_devs = [d for d in devs if d != "default"]
    assert len(chip_devs) >= 2, devs
    # per-chip counters are disjoint by construction: hits on a chip
    # can only come from pages inserted under that chip's label
    assert all(devs[d]["pages"] >= 0 for d in chip_devs)


def test_serial_no_pipelining_path_uses_cache(sc, monkeypatch):
    monkeypatch.setenv("SCANNER_TPU_NO_PIPELINING", "1")
    fc.set_page_frames(4)
    cold, warm = _ab(
        sc, lambda s, f: s.ops.Histogram(frame=f), "serial")
    assert warm >= N_FRAMES


def test_no_leaked_pins_after_runs(sc):
    fc.set_page_frames(4)
    fc.set_enabled(True)
    _run(sc, "pin_a", lambda s, f: s.ops.Histogram(frame=f))
    gc.collect()
    devs = fc.cache().status_dict()["devices"]
    assert all(d["pinned_bytes"] == 0 for d in devs.values()), devs


# ---------------------------------------------------------------------------
# chaos: memory.pressure with the cache armed (in-process cluster)
# ---------------------------------------------------------------------------

@pytest.fixture()
def fc_cluster(tmp_path, monkeypatch):
    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    from scanner_tpu import video as scv
    from scanner_tpu.engine.service import Master, Worker

    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=24, width=64, height=48,
                         fps=24, keyint=8)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("fcvid", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0,
                    metrics_port=0)
    addr = f"localhost:{master.port}"
    worker = Worker(addr, db_path=db_path, pipeline_instances=2)
    client = Client(db_path=db_path, master=addr)
    yield client, master
    faults.clear()
    client.stop()
    worker.stop()
    master.stop()


def _run_cluster(sc, name):
    import scanner_tpu.kernels  # noqa: F401
    frame = sc.io.Input([NamedVideoStream(sc, "fcvid")])
    h = sc.ops.Histogram(frame=frame)
    out = NamedStream(sc, name)
    sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return list(out.load())


@pytest.mark.chaos
def test_memory_pressure_with_cache_armed_bit_exact(fc_cluster):
    """The satellite chaos site: injected RESOURCE_EXHAUSTED during
    staging with the frame cache ARMED.  The first OOM lands in the
    best-effort page fill and is ABSORBED (the cache degrades, the
    task proceeds); the second lands in argument staging and requeues
    the task strike-free.  Output stays bit-exact either way, and
    /statusz carries the Frame-cache panel."""
    sc, master = fc_cluster
    fc.set_enabled(True)
    fc.set_page_frames(4)
    expect = _run_cluster(sc, "fc_clean")
    assert expect
    # drop the clean run's pages: a warm pool would serve every row
    # without staging and the fault site would never arm
    fc.cache().clear()

    transient_before = _counter("scanner_tpu_transient_retries_total")
    # one OOM in the page-fill path (match=cache) + one in argument
    # staging (match=staging) — the _stage detail leads with the kind
    faults.install(
        "memory.pressure:raise:exc=oom:match=cache:n=1:times=1;"
        "memory.pressure:raise:exc=oom:match=staging:n=1:times=1")
    got = _run_cluster(sc, "fc_faulted")
    fired = faults.fired("memory.pressure")
    faults.clear()

    assert fired == 2
    assert len(got) == len(expect)
    assert all(np.array_equal(a, b) for a, b in zip(got, expect))
    assert _counter("scanner_tpu_transient_retries_total") \
        >= transient_before + 1

    # /statusz Frame-cache panel (master role serves it; the pool
    # itself lives in the in-process worker — same process here)
    port = master.metrics_server.port
    st = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=10).read())
    assert "framecache" in st
    assert st["framecache"]["enabled"] is True
    assert isinstance(st["framecache"]["devices"], dict)
