"""Gang phase instrumentation + per-host straggler attribution
(docs/observability.md §Cross-host time; engine/gang.py phase spans,
engine/service.py `_fold_gang_phase_locked`, util/tracing.py
`gang_skew_summary`).

Layers:
  * fold units — the master's incremental per-(gang, epoch) fold fed
    synthetic gang.barrier/gang.collective spans: skew math, median
    lag, barrier- vs collective-bound attribution, clock-offset
    correction of member arrivals (a trustworthy offset flips which
    host is "slowest"; an untrustworthy one is ignored), bounded row
    retention, and parity with the dump-side `gang_skew_summary`;
  * metric units — `count_phases` / `observe_barrier_skew` series;
  * spawned e2e (slow) — the headline drill: a 2-host gang bulk with a
    `gang.collective` delay injected into ONE worker's member children
    (SCANNER_TPU_GANG_CHILD_FAULTS); the merged trace's barrier
    all-arrived events align within the published uncertainty after
    rebase, and the attribution rows name the delayed host as the
    barrier-bound slowest member.
"""

import os
import struct
import subprocess
import sys
import time

import cloudpickle
import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         PerfParams, register_op)
from scanner_tpu.engine import gang as egang
from scanner_tpu.engine.service import (MASTER_SERVICE,
                                        MAX_GANG_SKEW_ROWS, Master,
                                        _BulkJob)
from scanner_tpu.util import metrics as _mx
from scanner_tpu.util import tracing

cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.chaos

N_ROWS = 8


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="GangSkewDouble")
class GangSkewDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return _pk(2 * struct.unpack("<q", x)[0])


class _FoldHost:
    """The minimum `self` the fold method needs: the master-side
    per-node offset map (normally fed by heartbeats)."""

    def __init__(self, offsets=None):
        self._clock_offsets = dict(offsets or {})


def _bulk() -> _BulkJob:
    return _BulkJob(bulk_id=1, spec_blob=b"", task_timeout=0.0)


def _span(name, member, node, start, dur, gang=7, epoch=2, num=2):
    return {"name": name, "node": node, "start": start,
            "end": start + dur, "span_id": f"s{member}",
            "attrs": {"gang": gang, "epoch": epoch, "member": member,
                      "num": num, "job": 0, "task": 3}}


def _fold(host, bulk, spans):
    for d in spans:
        dur = max(d["end"] - d["start"], 0.0)
        Master._fold_gang_phase_locked(host, bulk, d["name"], d, dur)


def _skew_count() -> float:
    entry = _mx.registry().snapshot().get(
        "scanner_tpu_gang_barrier_skew_seconds", {})
    return sum(s.get("count", 0) for s in entry.get("samples", []))


# ---------------------------------------------------------------------------
# fold units
# ---------------------------------------------------------------------------

def test_fold_attributes_barrier_bound_slowest():
    bulk = _bulk()
    before = _skew_count()
    spans = [
        # member 0 arrives at 100.0 and waits 0.4 s for member 1
        _span("gang.barrier", 0, "workerA", 100.0, 0.4),
        _span("gang.barrier", 1, "workerB", 100.4, 0.0),
        _span("gang.collective", 0, "workerA", 100.4, 0.05),
        _span("gang.collective", 1, "workerB", 100.4, 0.05),
    ]
    _fold(_FoldHost(), bulk, spans)
    assert len(bulk.gang_skew_rows) == 1
    row = bulk.gang_skew_rows[0]
    assert row["gang"] == 7 and row["epoch"] == 2
    assert row["skew_s"] == pytest.approx(0.4)
    assert row["slowest"] == "workerB" and row["member"] == 1
    # lag vs the median arrival (mean of the two): 0.2 s
    assert row["lag_s"] == pytest.approx(0.2)
    assert row["bound"] == "barrier"      # skew 0.4 >= collective 0.05
    assert row["barrier_wait_max_s"] == pytest.approx(0.4)
    assert row["collective_max_s"] == pytest.approx(0.05)
    assert _skew_count() == before + 1


def test_fold_collective_bound_when_arrivals_tight():
    bulk = _bulk()
    spans = [
        _span("gang.barrier", 0, "workerA", 100.0, 0.001),
        _span("gang.barrier", 1, "workerB", 100.001, 0.0),
        _span("gang.collective", 0, "workerA", 100.0, 0.8),
        _span("gang.collective", 1, "workerB", 100.0, 0.9),
    ]
    _fold(_FoldHost(), bulk, spans)
    row = bulk.gang_skew_rows[0]
    assert row["bound"] == "collective"
    assert row["collective_max_s"] == pytest.approx(0.9)


def test_fold_corrects_arrivals_with_trusted_offsets():
    # raw stamps say workerB arrived 0.4 s late — but workerB's clock
    # runs 0.5 s AHEAD of the master (offset -0.5): on one clock it
    # actually arrived first, so workerA is the slowest member
    offsets = {"workerB": {"offset": -0.5, "uncertainty": 0.001}}
    bulk = _bulk()
    spans = [
        _span("gang.barrier", 0, "workerA", 100.0, 0.4),
        _span("gang.barrier", 1, "workerB", 100.4, 0.0),
        _span("gang.collective", 0, "workerA", 100.4, 0.01),
        _span("gang.collective", 1, "workerB", 100.4, 0.01),
    ]
    _fold(_FoldHost(offsets), bulk, spans)
    row = bulk.gang_skew_rows[0]
    assert row["slowest"] == "workerA"
    assert row["skew_s"] == pytest.approx(0.1)
    # an UNTRUSTWORTHY offset (uncertainty above the rebase threshold)
    # must be ignored — raw order stands
    offsets_bad = {"workerB": {"offset": -0.5, "uncertainty": 5.0}}
    bulk2 = _bulk()
    _fold(_FoldHost(offsets_bad), bulk2, spans)
    assert bulk2.gang_skew_rows[0]["slowest"] == "workerB"


def test_fold_prefers_bulk_scoped_offsets():
    # the span-batch-scoped estimate (shipped WITH the spans) wins over
    # the master's latest heartbeat estimate
    bulk = _bulk()
    bulk.clock_offsets["workerB"] = {"offset": -0.5,
                                     "uncertainty": 0.001}
    stale = {"workerB": {"offset": 0.0, "uncertainty": 0.001}}
    spans = [
        _span("gang.barrier", 0, "workerA", 100.0, 0.4),
        _span("gang.barrier", 1, "workerB", 100.4, 0.0),
        _span("gang.collective", 0, "workerA", 100.4, 0.01),
        _span("gang.collective", 1, "workerB", 100.4, 0.01),
    ]
    _fold(_FoldHost(stale), bulk, spans)
    assert bulk.gang_skew_rows[0]["slowest"] == "workerA"


def test_fold_incomplete_and_malformed_spans():
    bulk = _bulk()
    host = _FoldHost()
    # only one member reported: no row, no histogram observation
    before = _skew_count()
    _fold(host, bulk, [
        _span("gang.barrier", 0, "workerA", 100.0, 0.1),
        _span("gang.collective", 0, "workerA", 100.1, 0.05),
    ])
    assert bulk.gang_skew_rows == []
    assert _skew_count() == before
    # malformed attrs never raise, never fold
    Master._fold_gang_phase_locked(
        host, bulk, "gang.barrier",
        {"name": "gang.barrier", "attrs": {"gang": "x"}}, 0.0)
    Master._fold_gang_phase_locked(
        host, bulk, "gang.barrier", {"name": "gang.barrier"}, 0.0)
    assert bulk.gang_skew_rows == []
    # late duplicates after the fold finalized are ignored
    _fold(host, bulk, [
        _span("gang.barrier", 1, "workerB", 100.2, 0.0),
        _span("gang.collective", 1, "workerB", 100.2, 0.05),
    ])
    assert len(bulk.gang_skew_rows) == 1
    rows_before = list(bulk.gang_skew_rows)
    _fold(host, bulk, [_span("gang.barrier", 0, "workerA", 200.0, 0.1)])
    assert bulk.gang_skew_rows == rows_before


def test_fold_bounds_rows_and_arrival_map():
    bulk = _bulk()
    host = _FoldHost()
    n_epochs = MAX_GANG_SKEW_ROWS + 6
    for ep in range(n_epochs):
        _fold(host, bulk, [
            _span("gang.barrier", 0, "workerA", 100.0, 0.1, epoch=ep),
            _span("gang.barrier", 1, "workerB", 100.1, 0.0, epoch=ep),
            _span("gang.collective", 0, "workerA", 100.1, 0.01,
                  epoch=ep),
            _span("gang.collective", 1, "workerB", 100.1, 0.01,
                  epoch=ep),
        ])
    assert len(bulk.gang_skew_rows) == MAX_GANG_SKEW_ROWS
    # newest epochs survive the trim
    assert bulk.gang_skew_rows[-1]["epoch"] == n_epochs - 1
    assert bulk.gang_skew_rows[0]["epoch"] == n_epochs \
        - MAX_GANG_SKEW_ROWS


def test_dump_side_summary_matches_master_fold():
    spans = [
        _span("gang.barrier", 0, "workerA", 100.0, 0.4),
        _span("gang.barrier", 1, "workerB", 100.4, 0.0),
        _span("gang.collective", 0, "workerA", 100.4, 0.05),
        _span("gang.collective", 1, "workerB", 100.4, 0.05),
    ]
    bulk = _bulk()
    _fold(_FoldHost(), bulk, spans)
    dump_rows = tracing.gang_skew_summary(spans)
    assert dump_rows == bulk.gang_skew_rows
    # and straggler_summary surfaces the same rows under "gangs"
    s = tracing.straggler_summary(spans)
    assert s["gangs"] == dump_rows
    # incomplete dumps yield no partial rows
    assert tracing.gang_skew_summary(spans[:2]) == []


# ---------------------------------------------------------------------------
# metric units
# ---------------------------------------------------------------------------

def test_count_phases_folds_member_results():
    def phase(name, role="member"):
        entry = _mx.registry().snapshot().get(
            "scanner_tpu_gang_phase_seconds_total", {})
        for s in entry.get("samples", []):
            if s["labels"] == {"phase": name, "role": role}:
                return s["value"]
        return 0.0

    r0 = phase("rendezvous", "coordinator")
    b0 = phase("barrier")
    egang.count_phases({"rendezvous": 1.5, "barrier": 0.25,
                        "bogus": "nan?"}, "coordinator")
    assert phase("rendezvous", "coordinator") == pytest.approx(r0 + 1.5)
    egang.count_phases({"barrier": 0.75}, None)   # None -> "member"
    assert phase("barrier") == pytest.approx(b0 + 0.75)
    egang.count_phases(None, "member")            # no-op, no raise


def test_observe_barrier_skew_clamps_negative():
    before = _skew_count()
    egang.observe_barrier_skew(-0.5)
    egang.observe_barrier_skew(0.002)
    assert _skew_count() == before + 2


def test_gang_phase_series_declared():
    # SC314's contract: the series the instrumentation owns are
    # declared next to it
    assert "scanner_tpu_gang_phase_seconds_total" \
        in egang.GANG_PHASE_SERIES
    assert "scanner_tpu_gang_barrier_skew_seconds" \
        in egang.GANG_PHASE_SERIES


# ---------------------------------------------------------------------------
# spawned e2e: the headline drill
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gang_e2e_injected_delay_attributed_to_host(tmp_path):
    """2-host gang bulk; ONE worker's member children delay 1.2 s
    before entering the barrier (SCANNER_TPU_GANG_CHILD_FAULTS rides
    the gang.collective site, injected pre-barrier).  Afterwards:

      (a) the merged, clock-rebased trace shows barrier all-arrived
          events aligned within the published per-node uncertainty;
      (b) the master's attribution rows name the delayed worker's node
          as the barrier-bound slowest member, lagging ~the delay.
    """
    from scanner_tpu.engine.rpc import RpcClient, wait_for_server
    from scanner_tpu.util.jaxenv import cpu_only_env

    delay = 1.2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("gskew_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    env = cpu_only_env()
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SCANNER_TPU_FAULTS", None)
    env.pop("SCANNER_TPU_GANG_CHILD_FAULTS", None)
    env["SCANNER_TPU_GANG_INIT_TIMEOUT"] = "30"
    env["SCANNER_TPU_GANG_FORM_TIMEOUT"] = "6"
    master = Master(db_path=db_path, no_workers_timeout=30.0)
    addr = f"localhost:{master.port}"

    def spawn(extra_env=None):
        e = dict(env)
        e.update(extra_env or {})
        return subprocess.Popen(
            [sys.executable,
             os.path.join(repo, "tests", "spawn_worker.py"), addr,
             db_path], env=e, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

    # worker 0 clean; worker 1's member CHILDREN get the delay plan
    procs = [spawn(), spawn({
        "SCANNER_TPU_GANG_CHILD_FAULTS":
            f"gang.collective:delay:seconds={delay}"})]
    sc = None
    try:
        wait_for_server(addr, MASTER_SERVICE, timeout=60.0)
        sc = Client(db_path=db_path, master=addr)
        deadline = time.time() + 300
        while time.time() < deadline \
                and sc.job_status().get("num_workers", 0) < 2:
            time.sleep(0.25)
        assert sc.job_status()["num_workers"] == 2
        col = sc.io.Input([NamedStream(sc, "gskew_src")])
        col = sc.ops.GangSkewDouble(x=col)
        out = NamedStream(sc, "gskew_out")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.manual(4, N_ROWS // 2, gang_hosts=2),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        rows = [bytes(r) for r in out.load()]
        assert rows == [_pk(2 * (100 + i)) for i in range(N_ROWS)]

        # (b) attribution: every completed gang row is barrier-bound
        # with a lag in the ballpark of the injected delay, and they
        # all blame the SAME node (the armed worker)
        status = sc.job_status()
        gangs = (status.get("stragglers") or {}).get("gangs") or []
        assert gangs, "no gang attribution rows on GetJobStatus"
        blamed = {g["slowest"] for g in gangs}
        assert len(blamed) == 1, f"blame spread across {blamed}"
        for g in gangs:
            assert g["bound"] == "barrier", g
            assert g["skew_s"] >= delay * 0.5, g
            assert g["lag_s"] >= delay * 0.25, g

        # (a) merged rebased trace: barrier enter events split by the
        # delay, all-arrived events aligned within the published
        # uncertainty (+ scheduling slop)
        cl = RpcClient(addr, MASTER_SERVICE, timeout=30.0)
        try:
            reply = cl.try_call("GetTrace", bulk_id=None, retries=1)
        finally:
            cl.close()
        assert reply is not None and "spans" in reply
        offs = reply.get("clock_offsets") or {}
        assert offs, "no clock offsets reached trace assembly"
        for est in offs.values():
            assert est["uncertainty"] < 0.25
        budget = sum(e["uncertainty"] for e in offs.values()) + 0.25
        by_epoch = {}
        for d in reply["spans"]:
            if d.get("name") != "gang.barrier":
                continue
            a = d.get("attrs") or {}
            for ev in d.get("events") or []:
                if ev.get("name") == "barrier.all_arrived":
                    by_epoch.setdefault(
                        (a.get("gang"), a.get("epoch")), []).append(
                            (ev["t"], d.get("node")))
        complete = {k: v for k, v in by_epoch.items() if len(v) >= 2}
        assert complete, "no complete barrier in the merged trace"
        for (gid, ep), stamps in complete.items():
            ts = sorted(t for t, _ in stamps)
            assert ts[-1] - ts[0] <= budget, (
                f"gang {gid} epoch {ep}: all-arrived spread "
                f"{ts[-1] - ts[0]:.3f}s > budget {budget:.3f}s")
        # the trace's latest barrier ENTER per epoch names the same
        # node the master blamed
        rows_by_key = {(g["gang"], g["epoch"]): g for g in gangs}
        checked = 0
        for d in reply["spans"]:
            if d.get("name") != "gang.barrier":
                continue
            a = d.get("attrs") or {}
            row = rows_by_key.get((a.get("gang"), a.get("epoch")))
            if row is not None and a.get("member") == row["member"]:
                assert d.get("node") == row["slowest"]
                checked += 1
        assert checked, "no barrier span matched an attribution row"
    finally:
        if sc is not None:
            sc.stop()
        seed.stop()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
        master.stop()
