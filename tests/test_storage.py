import numpy as np
import pytest

from scanner_tpu.common import NullElement, StorageException
from scanner_tpu.storage import (ColumnDescriptor, ColumnType, Database,
                                 MemoryStorage, PosixStorage)
from scanner_tpu.storage import items, metadata as md


def test_posix_atomic_roundtrip(tmp_path):
    s = PosixStorage(str(tmp_path))
    s.write("a/b/c.bin", b"hello")
    assert s.read("a/b/c.bin") == b"hello"
    assert s.read_range("a/b/c.bin", 1, 3) == b"ell"
    assert s.exists("a/b/c.bin")
    assert s.size("a/b/c.bin") == 5
    assert s.list_prefix("a") == ["a/b/c.bin"]
    s.delete_prefix("a")
    assert not s.exists("a/b/c.bin")


@pytest.mark.parametrize("make", [
    lambda p: PosixStorage(str(p)), lambda p: MemoryStorage()])
def test_write_exclusive_first_writer_wins(tmp_path, make):
    s = make(tmp_path)
    assert s.write_exclusive("m/marker", b"video") is True
    assert s.write_exclusive("m/marker", b"pickle") is False
    assert s.read("m/marker") == b"video"
    # concurrent creators: exactly one wins
    import threading
    wins = []
    def race(i):
        if s.write_exclusive("m/race", f"w{i}".encode()):
            wins.append(i)
    ts = [threading.Thread(target=race, args=(i,)) for i in range(8)]
    [t.start() for t in ts]; [t.join() for t in ts]
    assert len(wins) == 1
    assert s.read("m/race") == f"w{wins[0]}".encode()


def test_item_format_roundtrip():
    s = MemoryStorage()
    rows = [b"abc", NullElement(), b"", b"xyz" * 100]
    items.write_item(s, "it", rows)
    out = items.read_item(s, "it")
    assert out == [b"abc", None, b"", b"xyz" * 100]
    assert items.item_num_rows(s, "it") == 4
    # sparse read
    sel = items.read_item_rows(s, "it", [3, 0, 1], sparsity_threshold=1)
    assert sel == [b"xyz" * 100, b"abc", None]
    # dense read path
    sel = items.read_item_rows(s, "it", [3, 0], sparsity_threshold=100)
    assert sel == [b"xyz" * 100, b"abc"]


def test_new_table_and_load(tmp_db):
    db = tmp_db
    db.new_table("t", ["col1", "col2"],
                 [[b"r00", b"r01"], [b"r10", b"r11"]])
    desc = db.table_descriptor("t")
    assert desc.num_rows == 2
    assert desc.column_names() == ["col1", "col2"]
    assert db.table_is_committed("t")
    assert list(db.load_column("t", "col2")) == [b"r01", b"r11"]
    assert list(db.load_column("t", "col1", rows=[1])) == [b"r10"]
    with pytest.raises(StorageException):
        db.new_table("t", ["c"], [[b"x"]])
    db.new_table("t", ["c"], [[b"x"]], overwrite=True)
    assert list(db.load_column("t", "c")) == [b"x"]


def test_multi_item_table(tmp_db):
    db = tmp_db
    cols = [ColumnDescriptor("data", ColumnType.BYTES)]
    desc = db.create_table("multi", cols, end_rows=[3, 5, 9])
    for item_idx, (s, e) in enumerate([(0, 3), (3, 5), (5, 9)]):
        rows = [f"row{r}".encode() for r in range(s, e)]
        items.write_item(db.backend,
                         md.column_item_path(desc.id, "data", item_idx), rows)
    db.commit_table("multi")
    assert [r.decode() for r in db.load_column("multi", "data")] == \
        [f"row{r}" for r in range(9)]
    # cross-item gather preserving request order
    got = list(db.load_column("multi", "data", rows=[8, 0, 4, 3]))
    assert [g.decode() for g in got] == ["row8", "row0", "row4", "row3"]
    assert desc.item_of_row(2) == 0
    assert desc.item_of_row(3) == 1
    assert desc.item_of_row(8) == 2


def test_commit_visibility_and_delete(tmp_db):
    db = tmp_db
    desc = db.create_table("u", [ColumnDescriptor("c")], end_rows=[1])
    assert db.has_table("u") and not db.table_is_committed("u")
    db.commit_table("u")
    assert db.table_is_committed("u")
    db.delete_table("u")
    assert not db.has_table("u")
    # id not reused
    d2 = db.create_table("u2", [ColumnDescriptor("c")], end_rows=[1])
    assert d2.id == desc.id + 1


def test_meta_persistence(tmp_path):
    s = PosixStorage(str(tmp_path))
    db = Database(s)
    db.new_table("t", ["c"], [[b"v"]])
    db.write_megafile()
    # fresh instance sees the same state
    db2 = Database(PosixStorage(str(tmp_path)))
    db2.load_megafile()
    assert db2.table_is_committed("t")
    assert list(db2.load_column("t", "c")) == [b"v"]


def test_video_descriptor_roundtrip():
    vd = md.VideoDescriptor(
        width=640, height=480, fps=29.97, num_frames=10, codec="h264",
        extradata=b"\x01\x02", sample_offsets=np.arange(10, dtype=np.uint64),
        sample_sizes=np.full(10, 7, np.uint64),
        keyframe_indices=np.array([0, 5], np.int64),
        sample_pts=np.arange(10, dtype=np.int64))
    vd2 = md.VideoDescriptor.deserialize(vd.serialize())
    assert vd2.width == 640 and vd2.fps == pytest.approx(29.97)
    assert (vd2.sample_offsets == vd.sample_offsets).all()
    assert (vd2.keyframe_indices == np.array([0, 5])).all()


def test_posix_write_exclusive_without_hardlinks(tmp_path, monkeypatch):
    """gcsfuse/NFS mounts reject os.link (EPERM/ENOTSUP); the marker path
    must fall back to O_CREAT|O_EXCL instead of erroring (frame-sink mode
    arbitration would otherwise break on those filesystems)."""
    import errno
    import os as _os

    from scanner_tpu.storage import PosixStorage

    def no_link(src, dst, **kw):
        raise OSError(errno.EPERM, "Operation not permitted")

    monkeypatch.setattr(_os, "link", no_link)
    s = PosixStorage(str(tmp_path / "db"))
    assert s.write_exclusive("m/marker", b"video") is True
    assert s.write_exclusive("m/marker", b"pickle") is False
    assert s.read("m/marker") == b"video"


def test_backend_base_write_exclusive_default():
    """Third-party backends that predate write_exclusive get a working
    (best-effort) default from the base class instead of
    NotImplementedError at save time."""
    from scanner_tpu.storage.backend import StorageBackend

    class Minimal(StorageBackend):
        def __init__(self):
            self.blobs = {}

        def exists(self, path):
            return path in self.blobs

        def write(self, path, data):
            self.blobs[path] = bytes(data)

        def read(self, path):
            return self.blobs[path]

    s = Minimal()
    assert s.write_exclusive("m", b"a") is True
    assert s.write_exclusive("m", b"b") is False
    assert s.read("m") == b"a"
