"""The driver contract of bench.py: ONE parseable JSON line on stdout
with metric/value/unit/vs_baseline, config selection via BENCH_CONFIGS,
and the capture-replay path when the tunnel is down."""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_configs_selection(monkeypatch):
    bench = _load_bench()
    monkeypatch.setenv("BENCH_CONFIGS", "all")
    assert bench._configs() == [1, 2, 3, 4, 5, 6, 7]
    monkeypatch.setenv("BENCH_CONFIGS", "3,1")
    assert bench._configs() == [1, 3]
    monkeypatch.setenv("BENCH_CONFIGS", "")
    assert bench._configs() == [1, 3]  # falls back to the default


def test_capture_replay_emits_one_json_line(capsys):
    """With the committed hardware capture present, the tunnel-down path
    must emit exactly one stdout line parseable as the north-star metric
    (the driver records this verbatim)."""
    bench = _load_bench()
    assert os.path.exists(bench.CAPTURE_PATH), \
        "committed BENCH_TPU_CAPTURE.json missing"
    assert bench._report_capture() is True
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, out
    rec = json.loads(out[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    assert rec["unit"] == "frames/sec/chip"
    assert rec["source"] == "opportunistic_capture"
    assert rec["value"] > 0
