"""Driver contract: entry() compiles single-chip; dryrun_multichip runs a
full sharded train step on the virtual mesh."""

import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")
import __graft_entry__ as graft


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
    assert np.isfinite(np.asarray(out, np.float32)).all()


@pytest.mark.slow  # ~2 min: full sharded train step over the virtual mesh
def test_dryrun_multichip():
    # n=8 exercises all three mesh axes (dp/sp/tp); smaller n collapse
    # axes to 1 and were verified manually (they also triple suite time)
    graft.dryrun_multichip(8)
