"""Benchmark: end-to-end histogram pipeline, frames/sec/chip.

BASELINE.json's metric is "frames/sec/chip (pose-detect + histogram
pipelines)".  The reference repo publishes no numbers (BASELINE.md); the
SIGGRAPH 2018 paper's GPU histogram throughput is on the order of 1000
frames/sec/GPU, used here as the nominal baseline for vs_baseline.

Runs on whatever JAX platform the environment provides (the real TPU chip
under the driver).  Prints ONE JSON line.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

BASELINE_FPS = 1000.0
N_FRAMES = int(os.environ.get("BENCH_FRAMES", "600"))
W, H = 640, 480
TPU_PROBE_TIMEOUT = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "120"))


def _tpu_reachable() -> bool:
    """Probe TPU init in a subprocess so a wedged tunnel cannot hang the
    bench; on failure the run falls back to CPU (the pipeline is
    decode-bound, so the number stays meaningful) and says so on stderr."""
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=TPU_PROBE_TIMEOUT, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return True
    except Exception:
        return False


def main():
    if not _tpu_reachable():
        print("bench: TPU backend unreachable, falling back to CPU",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    root = tempfile.mkdtemp(prefix="scbench_")
    try:
        from scanner_tpu import (CacheMode, Client, NamedStream,
                                 NamedVideoStream, PerfParams)
        import scanner_tpu.kernels  # registers Histogram

        vid = os.path.join(root, "bench.mp4")
        from scanner_tpu import video as scv
        scv.synthesize_video(vid, num_frames=N_FRAMES, width=W, height=H,
                             fps=30, keyint=30)
        sc = Client(db_path=os.path.join(root, "db"),
                    num_load_workers=3, num_save_workers=1)
        sc.ingest_videos([("bench", vid)])

        def run_once(name):
            frame = sc.io.Input([NamedVideoStream(sc, "bench")])
            hist = sc.ops.Histogram(frame=frame)
            out = NamedStream(sc, name)
            t0 = time.time()
            sc.run(sc.io.Output(hist, [out]), PerfParams.manual(32, 96),
                   cache_mode=CacheMode.Overwrite, show_progress=False)
            return time.time() - t0

        run_once("warmup")        # compile + cache warm
        dt = run_once("bench_out")
        fps = N_FRAMES / dt
        print(json.dumps({
            "metric": "histogram_pipeline_throughput",
            "value": round(fps, 2),
            "unit": "frames/sec/chip",
            "vs_baseline": round(fps / BASELINE_FPS, 4),
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
