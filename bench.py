"""Benchmarks: end-to-end pipeline throughput, frames/sec/chip.

BASELINE.json's north-star metric is "frames/sec/chip (pose-detect +
histogram pipelines)"; the reference repo publishes no numbers
(BASELINE.md), so the SIGGRAPH 2018 paper's ~1000 frames/sec/GPU
histogram throughput anchors vs_baseline.

Configs (BASELINE.md table):
  1 histogram      Histogram over the decoded stream
  2 shot           Histogram -> HistogramDelta temporal-diff chain
  3 pose           PoseDetect with the shipped trained weights
  4 objdet         ObjectDetect (SSD head + fixed-shape NMS)
  5 face           FaceEmbedding
  6 corpus         Histogram over a multi-video corpus in ONE bulk run
                   (BENCH_CORPUS_VIDEOS jobs through the scheduler +
                   pipeline — the corpus-shaped workload of the north
                   star, scaled to bench time)
  7 segment        InstanceSegment (detection + per-roi masks — the
                   detectron-app analog)

Prints ONE JSON line for the north-star metric (configs 1+3 averaged);
per-config detail goes to stderr and BENCH_DETAIL.json.  BENCH_CONFIGS
selects configs ("1,3" default; "all" = 1-7 incl. the corpus run);
BENCH_FRAMES / BENCH_MODEL_FRAMES / BENCH_CORPUS_VIDEOS size the decode
workloads.

Runs on whatever JAX platform the environment provides (the real TPU chip
under the driver); a wedged accelerator tunnel is probed in a subprocess
and falls back to CPU with a stderr note.  If the tunnel is down but an
opportunistic hardware capture from earlier in the round exists
(BENCH_TPU_CAPTURE.json, written by tools/tpu_capture.py), its TPU
numbers are reported as the metric of record — clearly labeled with the
capture timestamp — instead of a CPU fallback: the metric tracks what the
framework does on hardware, not whether the tunnel happened to be healthy
in the bench minute.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

BASELINE_FPS = 1000.0
N_FRAMES = int(os.environ.get("BENCH_FRAMES", "600"))
# model configs run conv nets per frame; smaller default keeps CPU
# fallback runs bounded while still amortizing compile on TPU
N_MODEL_FRAMES = int(os.environ.get("BENCH_MODEL_FRAMES", "128"))
W, H = 640, 480
TPU_PROBE_TIMEOUT = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "120"))
POSE_WEIGHTS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "scanner_tpu", "models",
    "weights", "pose_blobnet_w8.npz")


def _tpu_reachable() -> bool:
    """Probe TPU init in a subprocess so a wedged tunnel cannot hang the
    bench; on failure the run falls back to CPU (decode-bound configs stay
    meaningful) and says so on stderr."""
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=TPU_PROBE_TIMEOUT, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return True
    except Exception:
        return False


N_CORPUS_VIDEOS = int(os.environ.get("BENCH_CORPUS_VIDEOS", "8"))
N_CORPUS_FRAMES = int(os.environ.get("BENCH_CORPUS_FRAMES", "120"))


def _configs():
    sel = os.environ.get("BENCH_CONFIGS", "1,3").strip().lower()
    if sel == "all":
        return [1, 2, 3, 4, 5, 6, 7]
    picked = sorted({int(x) for x in sel.split(",") if x})
    if not picked:
        print(f"bench: empty BENCH_CONFIGS={sel!r}; using default 1,3",
              file=sys.stderr)
        return [1, 3]
    return picked


CAPTURE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_TPU_CAPTURE.json")


def _report_capture() -> bool:
    """Report an earlier same-round hardware capture when the tunnel is
    down now; returns False if no usable capture exists."""
    try:
        with open(CAPTURE_PATH) as f:
            cap = json.load(f)
        headline = dict(cap["headline"])
        if not any(d.get("platform") == "tpu" for d in cap.get("detail", [])):
            return False
    except Exception:
        return False
    print(f"bench: tunnel down now; reporting hardware capture from "
          f"{cap.get('captured_at')} (tools/tpu_capture.py)",
          file=sys.stderr)
    for d in cap.get("detail", []):
        print(f"bench: config {d['config']}: {d['fps']} fps "
              f"({d['frames']} frames, {d['platform']}, captured)",
              file=sys.stderr)
    headline["source"] = "opportunistic_capture"
    headline["captured_at"] = cap.get("captured_at")
    print(json.dumps(headline))
    return True


# model configs: engine op + constructor args (must match pipeline())
_MODEL_CFG_OPS = {3: ("PoseDetect", {"width": 8}),
                  4: ("ObjectDetect", {"width": 8}),
                  5: ("FaceEmbedding", {"width": 8}),
                  7: ("InstanceSegment", {"width": 8})}
# peak dense bf16 FLOP/s per chip by generation (public spec sheets)
_PEAK_BF16 = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}


def _annotate_mfu(detail, platform):
    """Attach model FLOPs/frame, achieved TFLOP/s and (on TPU) MFU to
    each model config's record: the configs that most need the chip
    carry a utilization number, not just fps.  FLOPs come from XLA's
    own cost analysis of the kernel's jitted inference
    (models/*.infer_cost_flops)."""
    import jax
    import numpy as np

    from scanner_tpu.common import DeviceType
    from scanner_tpu.graph.ops import KernelConfig, registry

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    peak = _PEAK_BF16.get(gen) if platform == "tpu" else None
    batch = np.zeros((32, H, W, 3), np.uint8)
    cfg = KernelConfig(device=DeviceType.TPU, devices=list(jax.devices()))
    for d in detail:
        op = _MODEL_CFG_OPS.get(d.get("config"))
        if op is None:
            continue
        name, kw = op
        try:
            kern = registry.get(name).kernel_factory(cfg, **kw)
            flops = kern.infer_cost_flops(batch)
        except Exception as e:  # noqa: BLE001 — never fail the bench
            d["mfu_error"] = f"{type(e).__name__}: {str(e)[:120]}"
            continue
        if not flops:
            continue
        per_frame = flops / len(batch)
        d["model_flops_per_frame"] = round(per_frame)
        d["achieved_tflops"] = round(per_frame * d["fps"] / 1e12, 4)
        if peak:
            d["mfu"] = round(per_frame * d["fps"] / peak, 6)
            d["peak_tflops"] = peak / 1e12


def main():
    if not _tpu_reachable():
        print("bench: TPU backend unreachable, falling back to CPU",
              file=sys.stderr)
        if _report_capture():
            return
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    platform = None
    root = tempfile.mkdtemp(prefix="scbench_")
    try:
        from scanner_tpu import (CacheMode, Client, NamedStream,
                                 NamedVideoStream, PerfParams)
        import scanner_tpu.kernels   # Histogram/HistogramDelta/...
        import scanner_tpu.models    # PoseDetect/ObjectDetect/FaceEmbedding
        from scanner_tpu import video as scv

        platform = jax.devices()[0].platform
        vid = os.path.join(root, "bench.mp4")
        scv.synthesize_video(vid, num_frames=N_FRAMES, width=W, height=H,
                             fps=30, keyint=32)
        sc = Client(db_path=os.path.join(root, "db"),
                    num_load_workers=3, num_save_workers=1)
        _, _ing_failed = sc.ingest_videos([("bench", vid)])
        assert not _ing_failed, _ing_failed

        def pipeline(config: int, frames_col):
            if config == 1:
                return sc.ops.Histogram(frame=frames_col)
            if config == 2:
                hist = sc.ops.Histogram(frame=frames_col)
                return sc.ops.HistogramDelta(hist=hist)
            if config == 3:
                if not os.path.exists(POSE_WEIGHTS):
                    # still measurable perf-wise, but flag it loudly: a
                    # random-weight pose number is not the trained model
                    print(f"bench: WARNING shipped pose weights missing "
                          f"({POSE_WEIGHTS}); using random init",
                          file=sys.stderr)
                return sc.ops.PoseDetect(
                    frame=frames_col, width=8,
                    checkpoint_dir=POSE_WEIGHTS
                    if os.path.exists(POSE_WEIGHTS) else None)
            if config == 4:
                # width 8 restores the shipped trained weights by default
                return sc.ops.ObjectDetect(frame=frames_col, width=8)
            if config == 5:
                return sc.ops.FaceEmbedding(frame=frames_col, width=8)
            if config == 7:
                return sc.ops.InstanceSegment(frame=frames_col, width=8)
            raise ValueError(config)

        def run_corpus() -> dict:
            """Config 6: one bulk run over a multi-video corpus — jobs
            stream through the scheduler and the pipeline overlaps
            decode/eval/save ACROSS jobs (the corpus-shaped workload of
            the north-star metric, scaled to bench time)."""
            # one encode, N table names: the corpus shape matters to
            # the scheduler/pipeline, not the bytes
            p = os.path.join(root, "corpus.mp4")
            scv.synthesize_video(p, num_frames=N_CORPUS_FRAMES,
                                 width=W, height=H, fps=30, keyint=32)
            names = [(f"corpus_{i}", p) for i in range(N_CORPUS_VIDEOS)]
            _, _ing_failed = sc.ingest_videos(names)
            assert not _ing_failed, _ing_failed

            def run_once(suffix: str) -> float:
                streams = [NamedVideoStream(sc, n) for n, _ in names]
                frames = sc.io.Input(streams)
                hist = sc.ops.Histogram(frame=frames)
                outs = [NamedStream(sc, f"c6_{n}_{suffix}")
                        for n, _ in names]
                t0 = time.time()
                sc.run(sc.io.Output(hist, outs), PerfParams.manual(32, 96),
                       cache_mode=CacheMode.Overwrite, show_progress=False)
                return time.time() - t0

            t_warm = run_once("w")
            dt = run_once("m")
            total = N_CORPUS_VIDEOS * N_CORPUS_FRAMES
            return {"config": 6, "frames": total,
                    "videos": N_CORPUS_VIDEOS, "keyint": 32,
                    "fps": round(total / dt, 2), "platform": platform,
                    "warmup_s": round(t_warm, 2),
                    "measured_s": round(dt, 2), "reps": 1,
                    "clock": time.strftime("%Y-%m-%dT%H:%M:%S"),
                    "host_cpus": os.cpu_count()}

        def run_config(config: int) -> dict:
            if config == 6:
                return run_corpus()
            n = N_FRAMES if config in (1, 2) else min(N_FRAMES,
                                                      N_MODEL_FRAMES)

            def run_once(name: str, rows: int) -> float:
                frames = sc.io.Input([NamedVideoStream(sc, "bench")])
                ranged = sc.streams.Range(frames, [(0, rows)])
                out = NamedStream(sc, name)
                t0 = time.time()
                sc.run(sc.io.Output(pipeline(config, ranged), [out]),
                       PerfParams.manual(32, 96),
                       cache_mode=CacheMode.Overwrite, show_progress=False)
                return time.time() - t0

            # Warmup pays the jit compile and (for the decode-bound
            # configs, where a full pass is cheap) warms the page cache so
            # runs compare warm-vs-warm across rounds.  Model configs only
            # need the compile: one full work packet (32 rows) plus the
            # measured run's tail-chunk shape (n % 32), so the timed run
            # never compiles.
            warm = n if config in (1, 2) or n <= 32 else 32 + (n % 32)
            t_warm = run_once(f"warmup_{config}", warm)
            dt = run_once(f"bench_{config}", n)
            d = {"config": config, "frames": n,
                 "fps": round(n / dt, 2), "platform": platform,
                 "keyint": 32,  # round-3+: packet-aligned GOPs (was 30)
                 "warmup_frames": warm,
                 "warmup_s": round(t_warm, 2), "measured_s": round(dt, 2),
                 "reps": 1, "clock": time.strftime("%Y-%m-%dT%H:%M:%S"),
                 "host_cpus": os.cpu_count()}
            if config == 3 and not os.path.exists(POSE_WEIGHTS):
                d["weights"] = "random"
            return d

        detail = [run_config(c) for c in _configs()]
        _annotate_mfu(detail, platform)
        for d in detail:
            print(f"bench: config {d['config']}: {d['fps']} fps "
                  f"({d['frames']} frames, {d['platform']})",
                  file=sys.stderr)
        # append the live-metrics registry so perf rounds get counters
        # (recompiles, retries, bytes moved, chunk-wait seconds)
        # alongside fps — the attribution PERF.md round 3 had to
        # reconstruct from traces ships with every bench run
        from scanner_tpu.util.metrics import labeled_samples, registry
        snap = registry().snapshot()

        def per_op(series: str) -> dict:
            # sum across the remaining labels (these series carry a
            # `device` label since the multichip round: last-sample-wins
            # would report one arbitrary chip's count and mask a
            # recompile storm confined to another); the per-device
            # breakdown ships in the `multichip` digest below
            out: dict = {}
            for s in snap.get(series, {}).get("samples", []):
                k = s["labels"].get("op", "_")
                out[k] = out.get(k, 0) + s["value"]
            return out

        # shape-stability digest: with bucketed dispatch (PERF.md §5)
        # recompiles must sit at ladder size per op whatever the task
        # geometry; pad_rows is the padding waste paid for that
        detail.append({
            "config": "shape_stability",
            "recompiles": per_op("scanner_tpu_op_recompiles_total"),
            "pad_rows": per_op("scanner_tpu_op_pad_rows_total"),
            "precompile_seconds":
                per_op("scanner_tpu_op_precompile_seconds"),
        })

        def per_labels(series: str) -> dict:
            return labeled_samples(snap, series)

        # multichip digest: did the bench's bulks actually spread across
        # this host's chips (evaluator affinity, PERF.md §6)?  tasks and
        # busy seconds per assigned device, plus per-(op, device)
        # executable counts — a chip at 0 while siblings climb is the
        # regression this series exists to catch
        try:
            import jax
            n_dev = len(jax.local_devices())
        except Exception:  # noqa: BLE001
            n_dev = None
        detail.append({
            "config": "multichip",
            "n_devices": n_dev,
            "affinity": os.environ.get(
                "SCANNER_TPU_DEVICE_AFFINITY", "1") not in ("0", "false"),
            "device_tasks": per_labels("scanner_tpu_device_tasks_total"),
            "device_busy_seconds":
                per_labels("scanner_tpu_device_busy_seconds_total"),
            "recompiles_by_device":
                per_labels("scanner_tpu_op_recompiles_total"),
        })
        # memory digest (util/memstats.py): peak HBM per device (backend
        # view), the allocation ledger's peaks per (device, kind) —
        # staged columns vs warm-up args vs sink batches — and the
        # padding waste bucketed dispatch paid, in approximate bytes
        # (pad rows x decoded-frame bytes; exact per-op row widths are
        # not knowable from counters alone)
        pad_rows_total = sum(
            s["value"] for s in snap.get(
                "scanner_tpu_op_pad_rows_total", {}).get("samples", []))
        from scanner_tpu.util import memstats as _memstats
        detail.append({
            "config": "memory",
            "device_hbm": _memstats.device_memory_stats(),
            "device_hbm_peak_bytes":
                per_labels("scanner_tpu_device_hbm_peak_bytes"),
            "ledger_peak_bytes":
                per_labels("scanner_tpu_ledger_peak_bytes"),
            "ledger_live_bytes":
                per_labels("scanner_tpu_ledger_live_bytes"),
            "staged_bytes_total": sum(
                s["value"] for s in snap.get(
                    "scanner_tpu_h2d_bytes_total", {}).get("samples", [])),
            "pad_rows_total": pad_rows_total,
            "pad_waste_bytes_approx": int(pad_rows_total * W * H * 3),
            "oom_events": sum(
                s["value"] for s in snap.get(
                    "scanner_tpu_device_oom_events_total",
                    {}).get("samples", [])),
        })

        # quantile estimation shared with the health/SLO engine and
        # tools (scanner_tpu.util.metrics.histogram_quantile)
        from scanner_tpu.util.metrics import snapshot_histogram_quantiles

        def hist_quantiles(series: str, qs=(0.5, 0.9, 0.99)) -> dict:
            return snapshot_histogram_quantiles(snap, series, qs)

        # end-to-end per-task latency digest (enqueue -> sink-committed):
        # the serving-mode p50/p99 seed (ROADMAP item 2) banked per
        # round so the latency trajectory ships with the fps one.
        # Computed once; the baseline_metrics entry below reuses it so
        # the two banked views can never disagree.
        _tlq = hist_quantiles("scanner_tpu_task_latency_seconds")
        detail.append({"config": "task_latency", **_tlq})
        # compute-efficiency digest (util/coststats.py): the roofline
        # table per (op, device, bucket) — achieved FLOP/s / bytes/s
        # and the compute-vs-memory-bound verdict — plus the compile
        # ledger summary with the persistent-cache hit rate.  The
        # baseline instrument the ROADMAP perf items (pjit mesh, Pallas
        # scan kernels, frame cache) are judged against.
        from scanner_tpu.util import coststats as _coststats
        _eff_ops = _coststats.op_efficiency()
        _csum = _coststats.ledger_summary()
        detail.append({
            "config": "op_efficiency",
            "ops": _eff_ops,
            "compile": _csum,
        })
        # frame-cache digest (engine/framecache.py): the cross-task
        # reuse A/B the acceptance gate reads — cache-on cold+warm
        # passes over the same clip vs a SCANNER_TPU_FRAME_CACHE=0 run,
        # with decode seconds and h2d bytes saved measured from the
        # shared counters (the cache bills the same h2d meter direct
        # staging does, so the comparison is like for like)
        from scanner_tpu.engine import framecache as _framecache

        def _fc_digest() -> dict:
            if not _framecache.enabled():
                return {"config": "frame_cache", "enabled": False}
            # CPU fallback: force device staging so the HBM-pool paths
            # run on the host backend too (the TPU path needs no help)
            prev_kd = os.environ.get("SCANNER_TPU_KERNEL_DEVICES")
            forced = platform != "tpu" and prev_kd != "all"
            if forced:
                os.environ["SCANNER_TPU_KERNEL_DEVICES"] = "all"
            n_fc = min(N_FRAMES, 288)

            def tot(name: str) -> float:
                s = registry().snapshot().get(name, {})
                return sum(x["value"] for x in s.get("samples", []))

            def measured(name: str) -> dict:
                d0 = tot("scanner_tpu_decode_seconds_total")
                b0 = tot("scanner_tpu_h2d_bytes_total")
                frames = sc.io.Input([NamedVideoStream(sc, "bench")])
                ranged = sc.streams.Range(frames, [(0, n_fc)])
                out = NamedStream(sc, name)
                t0 = time.time()
                sc.run(sc.io.Output(sc.ops.Histogram(frame=ranged),
                                    [out]), PerfParams.manual(32, 96),
                       cache_mode=CacheMode.Overwrite,
                       show_progress=False)
                return {
                    "wall_s": round(time.time() - t0, 3),
                    "decode_s": round(
                        tot("scanner_tpu_decode_seconds_total") - d0, 4),
                    "h2d_bytes": tot("scanner_tpu_h2d_bytes_total") - b0,
                }

            try:
                _framecache.cache().clear()
                h0 = tot("scanner_tpu_framecache_hits_total")
                m0 = tot("scanner_tpu_framecache_misses_total")
                on_cold = measured("fc_on_cold")
                h1 = tot("scanner_tpu_framecache_hits_total")
                m1 = tot("scanner_tpu_framecache_misses_total")
                on_warm = measured("fc_on_warm")
                h2 = tot("scanner_tpu_framecache_hits_total")
                m2 = tot("scanner_tpu_framecache_misses_total")
                hits, misses = h2 - h0, m2 - m0
                wh, wm = h2 - h1, m2 - m1
                _framecache.set_enabled(False)
                off = measured("fc_off")
                return {
                    "config": "frame_cache", "enabled": True,
                    "frames": n_fc,
                    # combined A/B rate (cold fill + warm reuse) AND the
                    # warm-pass rate — the hot-clip/second-pipeline
                    # scenario the cache exists for, and the number the
                    # acceptance gate + baseline direction track
                    "hit_rate": round(hits / (hits + misses), 4)
                    if hits + misses else None,
                    "warm_hit_rate": round(wh / (wh + wm), 4)
                    if wh + wm else None,
                    "hits": hits, "misses": misses,
                    "on_cold": on_cold, "on_warm": on_warm, "off": off,
                    "decode_seconds_saved": round(
                        off["decode_s"] - on_warm["decode_s"], 4),
                    "h2d_bytes_saved":
                        off["h2d_bytes"] - on_warm["h2d_bytes"],
                }
            finally:
                _framecache.set_enabled(True)
                if forced:
                    # restore EXACTLY what the user had set — popping a
                    # user-provided value would skew every later digest
                    if prev_kd is None:
                        os.environ.pop("SCANNER_TPU_KERNEL_DEVICES",
                                       None)
                    else:
                        os.environ["SCANNER_TPU_KERNEL_DEVICES"] = \
                            prev_kd

        _fc_d = _fc_digest()
        detail.append(_fc_d)

        # remediation digest (engine/controller.py): a bounded live
        # preemption drill — tiny in-process cluster, one of two
        # workers preempted mid-bulk (the worker.preempt chaos site) —
        # banking the recovery time (preemption notice -> bulk
        # complete, i.e. how fast the cluster re-absorbs reclaimed
        # capacity's work) plus the controller's decision counters, so
        # tools/bench_history.py gates the close-the-loop trajectory
        # like any other metric
        def _remediation_digest() -> dict:
            import struct as _struct
            import threading as _threading

            from scanner_tpu import Kernel, register_op
            from scanner_tpu.engine import controller as _ctrl
            from scanner_tpu.engine.service import Master, Worker
            from scanner_tpu.util import faults as _faults

            if not _ctrl.enabled():
                return {"config": "remediation", "enabled": False}

            def _pk(v: int) -> bytes:
                return _struct.pack("<q", v)

            @register_op(name="BenchRemSleep")
            class BenchRemSleep(Kernel):
                # slow enough that the bulk (24 tasks across 2
                # workers) outlives the 2nd-heartbeat preemption at
                # ~2 s — the drill must reclaim capacity MID-bulk
                def execute(self, x: bytes) -> bytes:
                    time.sleep(0.2)
                    return _pk(2 * _struct.unpack("<q", x)[0])

            def _tot(name: str) -> float:
                s = registry().snapshot().get(name, {})
                return sum(x["value"] for x in s.get("samples", []))

            def _by_labels(name: str) -> dict:
                return labeled_samples(registry().snapshot(), name)

            rdb = os.path.join(root, "rem_db")
            n_rows = 48
            seed2 = Client(db_path=rdb)
            seed2.new_table("rem_src", ["output"],
                            [[_pk(100 + i)] for i in range(n_rows)])
            master = Master(db_path=rdb, no_workers_timeout=30.0)
            addr = f"localhost:{master.port}"
            workers = [Worker(addr, db_path=rdb) for _ in range(2)]
            rc = Client(db_path=rdb, master=addr)
            strikes0 = _tot("scanner_tpu_blacklist_strikes_total")
            trans0 = {k: v for k, v in _by_labels(
                "scanner_tpu_alerts_transitions_total").items()}
            victim = workers[0]
            preempt_at = [None]

            def _watch() -> None:
                while preempt_at[0] is None:
                    if victim.preempting():
                        preempt_at[0] = time.time()
                        return
                    time.sleep(0.01)

            try:
                _faults.install(
                    f"worker.preempt:raise:"
                    f"match={victim.worker_id}:n=2:times=1")
                w_t = _threading.Thread(target=_watch, daemon=True)
                w_t.start()
                col = rc.io.Input([NamedStream(rc, "rem_src")])
                col = rc.ops.BenchRemSleep(x=col)
                out = NamedStream(rc, "rem_out")
                rc.run(rc.io.Output(col, [out]),
                       PerfParams.manual(2, 2),
                       cache_mode=CacheMode.Overwrite,
                       show_progress=False)
                done_at = time.time()
                rows_ok = len(list(out.load())) == n_rows
                recovery = round(done_at - preempt_at[0], 3) \
                    if preempt_at[0] is not None \
                    and preempt_at[0] < done_at else None
                trans1 = _by_labels(
                    "scanner_tpu_alerts_transitions_total")
                return {
                    "config": "remediation", "enabled": True,
                    "rows_ok": rows_ok,
                    "preemption_recovery_s": recovery,
                    "preemptions": _tot(
                        "scanner_tpu_worker_preemptions_total"),
                    "preempt_notices": _tot(
                        "scanner_tpu_worker_preempt_notices_total"),
                    "strike_delta": _tot(
                        "scanner_tpu_blacklist_strikes_total")
                    - strikes0,
                    "alert_transitions": {
                        k: v - trans0.get(k, 0.0)
                        for k, v in trans1.items()
                        if v - trans0.get(k, 0.0)},
                    "remediations": _by_labels(
                        "scanner_tpu_remediations_total"),
                }
            finally:
                _faults.clear()
                rc.stop()
                for w in workers:
                    w.stop()
                master.stop()

        try:
            _rem_d = _remediation_digest()
        except Exception as e:  # noqa: BLE001 — bench must not die on
            # the remediation drill
            _rem_d = {"config": "remediation",
                      "error": f"{type(e).__name__}: {e}"}
        detail.append(_rem_d)

        # failover digest (engine/journal.py): a bounded live master-
        # failover drill — in-process 2-worker cluster, the master
        # stopped abruptly mid-bulk (no checkpoint clear, journal-only
        # durability: checkpoint_frequency=0) and a successor started
        # on the same port — banking the recovery time (kill -> bulk
        # complete) and how many acknowledged completions the
        # successor failed to restore (the journal's whole point: 0),
        # so tools/bench_history.py gates the durable-control-plane
        # trajectory like any other metric
        def _failover_digest() -> dict:
            import socket as _socket
            import struct as _struct
            import threading as _threading

            from scanner_tpu import Kernel, register_op
            from scanner_tpu.engine.service import Master, Worker

            def _pk(v: int) -> bytes:
                return _struct.pack("<q", v)

            def _tot(name: str) -> float:
                s = registry().snapshot().get(name, {})
                return sum(x["value"] for x in s.get("samples", []))

            @register_op(name="BenchFoSleep")
            class BenchFoSleep(Kernel):
                # slow enough that the bulk (24 tasks across 2
                # workers) outlives the mid-bulk master kill
                def execute(self, x: bytes) -> bytes:
                    time.sleep(0.15)
                    return _pk(2 * _struct.unpack("<q", x)[0])

            fdb = os.path.join(root, "fo_db")
            n_rows = 48
            seedf = Client(db_path=fdb)
            seedf.new_table("fo_src", ["output"],
                            [[_pk(100 + i)] for i in range(n_rows)])
            with _socket.socket() as s:
                s.bind(("localhost", 0))
                port = s.getsockname()[1]
            m1 = Master(db_path=fdb, port=port, no_workers_timeout=60.0)
            addr = f"localhost:{port}"
            workers = [Worker(addr, db_path=fdb) for _ in range(2)]
            fc = Client(db_path=fdb, master=addr)
            result: dict = {}
            m2 = None

            def _job() -> None:
                try:
                    col = fc.io.Input([NamedStream(fc, "fo_src")])
                    col = fc.ops.BenchFoSleep(x=col)
                    out = NamedStream(fc, "fo_out")
                    fc.run(fc.io.Output(col, [out]),
                           PerfParams.manual(2, 2,
                                             checkpoint_frequency=0),
                           cache_mode=CacheMode.Overwrite,
                           show_progress=False)
                    result["rows"] = len(list(out.load()))
                except Exception as e:  # noqa: BLE001
                    result["error"] = f"{type(e).__name__}: {e}"

            try:
                jt = _threading.Thread(target=_job, daemon=True)
                jt.start()
                deadline = time.time() + 60
                while time.time() < deadline:
                    with m1._lock:
                        b = m1._bulk
                        if b is not None and len(b.done) >= 4:
                            break
                    time.sleep(0.02)
                m1.stop()  # abrupt: bulk still active, nothing cleared
                with m1._lock:
                    done_at_kill = len(m1._bulk.done) \
                        if m1._bulk else 0
                kill_at = time.time()
                # successor on the SAME port (workers redial it); the
                # just-freed port can linger briefly
                for _ in range(20):
                    try:
                        m2 = Master(db_path=fdb, port=port,
                                    no_workers_timeout=60.0)
                        break
                    except Exception:  # noqa: BLE001 — port lingering
                        time.sleep(0.25)
                restored = 0
                if m2 is not None:
                    with m2._lock:
                        restored = len(m2._bulk.done) \
                            if m2._bulk else 0
                jt.join(timeout=120)
                done_at = time.time()
                recovery = round(done_at - kill_at, 3) \
                    if result.get("rows") == n_rows else None
                return {
                    "config": "failover",
                    "rows_ok": result.get("rows") == n_rows,
                    "error": result.get("error"),
                    "done_at_kill": done_at_kill,
                    "done_restored": restored,
                    "tasks_lost_on_recovery":
                        max(0, done_at_kill - restored),
                    "failover_recovery_s": recovery,
                    "journal_appends": _tot(
                        "scanner_tpu_journal_appends_total"),
                    "journal_replayed": _tot(
                        "scanner_tpu_journal_replayed_records_total"),
                }
            finally:
                fc.stop()
                for w in workers:
                    w.stop()
                if m2 is not None:
                    m2.stop()

        try:
            _fo_d = _failover_digest()
        except Exception as e:  # noqa: BLE001 — bench must not die on
            # the failover drill
            _fo_d = {"config": "failover",
                     "error": f"{type(e).__name__}: {e}"}
        detail.append(_fo_d)

        # gang digest (engine/gang.py): a bounded live gang drill —
        # in-process 2-worker cluster running a gang_hosts=2 bulk, one
        # worker killed abruptly after the first gang formed — banking
        # formation seconds (submit -> first gang formed), reform
        # seconds after the injected host loss (kill -> next
        # formation, which includes the stale-scan detection window),
        # and epochs minted per bulk, so tools/bench_history.py gates
        # the gang-scheduling trajectory (`gang_reform_s`,
        # better=lower) like any other metric
        def _gang_digest() -> dict:
            import struct as _struct
            import threading as _threading

            from scanner_tpu import Kernel, register_op
            from scanner_tpu.engine import gang as _egang
            from scanner_tpu.engine.service import Master, Worker

            def _pk(v: int) -> bytes:
                return _struct.pack("<q", v)

            def _tot(name: str) -> float:
                s = registry().snapshot().get(name, {})
                return sum(x["value"] for x in s.get("samples", []))

            @register_op(name="BenchGangSleep")
            class BenchGangSleep(Kernel):
                def execute(self, x: bytes) -> bytes:
                    time.sleep(0.05)
                    return _pk(2 * _struct.unpack("<q", x)[0])

            gdb = os.path.join(root, "gang_db")
            n_rows = 16
            seedg = Client(db_path=gdb)
            seedg.new_table("gang_src", ["output"],
                            [[_pk(100 + i)] for i in range(n_rows)])
            m = Master(db_path=gdb, no_workers_timeout=60.0)
            addr = f"localhost:{m.port}"
            old_form = _egang.form_timeout_s()
            _egang.set_form_timeout_s(4.0)
            workers = [Worker(addr, db_path=gdb) for _ in range(2)]
            gc2 = Client(db_path=gdb, master=addr)
            result: dict = {}
            formed0 = _tot("scanner_tpu_gang_formed_total")
            aborted0 = _tot("scanner_tpu_gang_aborted_total")

            def _job() -> None:
                try:
                    col = gc2.io.Input([NamedStream(gc2, "gang_src")])
                    col = gc2.ops.BenchGangSleep(x=col)
                    out = NamedStream(gc2, "gang_out")
                    gc2.run(gc2.io.Output(col, [out]),
                            PerfParams.manual(4, 4, gang_hosts=2),
                            cache_mode=CacheMode.Overwrite,
                            show_progress=False)
                    result["rows"] = len(list(out.load()))
                except Exception as e:  # noqa: BLE001
                    result["error"] = f"{type(e).__name__}: {e}"

            try:
                submit = time.time()
                jt = _threading.Thread(target=_job, daemon=True)
                jt.start()
                formation_s = None
                victim = workers[1].worker_id
                deadline = time.time() + 90
                # wait until the victim is a member of a LIVE gang —
                # killing between a gang's completion and the next
                # formation would produce no abort and a null metric
                while time.time() < deadline:
                    if formation_s is None and _tot(
                            "scanner_tpu_gang_formed_total") > formed0:
                        formation_s = round(time.time() - submit, 3)
                    with m._lock:
                        b = m._bulk
                        live = b is not None and any(
                            victim in g.members
                            for g in b.gangs.values())
                    if formation_s is not None and live:
                        break
                    time.sleep(0.02)
                # injected host loss mid-gang: the victim stops AND the
                # master applies the loss immediately (the same path
                # the stale scan takes after its 6 s detection window —
                # excluded here so gang_reform_s measures the engine's
                # abort -> re-form work, not the detection constant)
                kill_at = time.time()
                workers[1].stop()
                _recs: list = []
                with m._lock:
                    w = m._workers.get(victim)
                    if w is not None:
                        w.active = False
                    m._requeue_worker_tasks(victim, recs=_recs)
                m._journal_append(_recs)
                reform_s = None
                formed_at_kill = _tot("scanner_tpu_gang_formed_total")
                deadline = time.time() + 90
                while time.time() < deadline:
                    if _tot("scanner_tpu_gang_aborted_total") \
                            > aborted0 \
                            and _tot("scanner_tpu_gang_formed_total") \
                            > formed_at_kill:
                        reform_s = round(time.time() - kill_at, 3)
                        break
                    time.sleep(0.02)
                jt.join(timeout=180)
                return {
                    "config": "gang",
                    "rows_ok": result.get("rows") == n_rows,
                    "error": result.get("error"),
                    "gang_formation_s": formation_s,
                    "gang_reform_s": reform_s,
                    "gangs_formed": _tot(
                        "scanner_tpu_gang_formed_total") - formed0,
                    "gangs_aborted": _tot(
                        "scanner_tpu_gang_aborted_total") - aborted0,
                    "epochs": _tot("scanner_tpu_gang_epoch"),
                    "stale_nacks": _tot(
                        "scanner_tpu_gang_stale_nacks_total"),
                }
            finally:
                _egang.set_form_timeout_s(old_form)
                gc2.stop()
                for w in workers:
                    w.stop()
                m.stop()

        try:
            _gang_d = _gang_digest()
        except Exception as e:  # noqa: BLE001 — bench must not die on
            # the gang drill
            _gang_d = {"config": "gang",
                       "error": f"{type(e).__name__}: {e}"}
        detail.append(_gang_d)

        # gang skew digest (util/clocksync.py + engine/gang.py phase
        # spans): a clean 2-worker gang_hosts=2 run — no injected loss
        # — banking the barrier-skew p99 the master observed from
        # offset-corrected member arrivals and the worst clock-offset
        # uncertainty any worker published, so tools/bench_history.py
        # gates the cross-host observability direction
        # (`gang_barrier_skew_p99_s` / `clock_offset_uncertainty_s`,
        # both better=lower).  Quantiles are over the process-global
        # histogram, which also holds the gang drill's clean epochs —
        # all uninjected skews, so the aggregate stays an honest
        # clean-run baseline.
        def _gang_skew_digest() -> dict:
            import struct as _struct

            from scanner_tpu import Kernel, register_op
            from scanner_tpu.engine import gang as _egang
            from scanner_tpu.engine.service import Master, Worker
            from scanner_tpu.util.metrics import (
                snapshot_histogram_quantiles as _shq)

            def _pk(v: int) -> bytes:
                return _struct.pack("<q", v)

            @register_op(name="BenchGangSkewSleep")
            class BenchGangSkewSleep(Kernel):
                def execute(self, x: bytes) -> bytes:
                    time.sleep(0.05)
                    return _pk(3 * _struct.unpack("<q", x)[0])

            sdb = os.path.join(root, "gang_skew_db")
            n_rows = 16
            seeds = Client(db_path=sdb)
            seeds.new_table("gskew_src", ["output"],
                            [[_pk(200 + i)] for i in range(n_rows)])
            m = Master(db_path=sdb, no_workers_timeout=60.0)
            addr = f"localhost:{m.port}"
            old_form = _egang.form_timeout_s()
            _egang.set_form_timeout_s(4.0)
            workers = [Worker(addr, db_path=sdb) for _ in range(2)]
            gc3 = Client(db_path=sdb, master=addr)
            result: dict = {}
            try:
                col = gc3.io.Input([NamedStream(gc3, "gskew_src")])
                col = gc3.ops.BenchGangSkewSleep(x=col)
                out = NamedStream(gc3, "gskew_out")
                try:
                    gc3.run(gc3.io.Output(col, [out]),
                            PerfParams.manual(4, 4, gang_hosts=2),
                            cache_mode=CacheMode.Overwrite,
                            show_progress=False)
                    result["rows"] = len(list(out.load()))
                except Exception as e:  # noqa: BLE001
                    result["error"] = f"{type(e).__name__}: {e}"
                # straggler attribution rows the master folded for this
                # bulk (gang/epoch/slowest/bound) — proves the
                # attribution path end to end in-process
                with m._lock:
                    b = m._bulk
                    if b is None and m._history:
                        b = m._history[max(m._history)]
                    skew_rows = (list(b.gang_skew_rows)
                                 if b is not None else [])
                # the uncertainty gauge appears once a worker has
                # heartbeat round-trips banked (~2 beats); give the
                # publication a bounded grace window
                deadline = time.time() + 10
                while time.time() < deadline:
                    gs = registry().snapshot().get(
                        "scanner_tpu_clock_offset_uncertainty_seconds",
                        {}).get("samples", [])
                    if gs:
                        break
                    time.sleep(0.1)
                fsnap = registry().snapshot()
                skq = _shq(
                    fsnap, "scanner_tpu_gang_barrier_skew_seconds")
                unc = [s["value"] for s in fsnap.get(
                    "scanner_tpu_clock_offset_uncertainty_seconds",
                    {}).get("samples", [])]
                return {
                    "config": "gang_skew",
                    "rows_ok": result.get("rows") == n_rows,
                    "error": result.get("error"),
                    "gang_barrier_skew_p99_s": skq.get("p99_s"),
                    "gang_barrier_skew_p50_s": skq.get("p50_s"),
                    "skews_observed": skq.get("count"),
                    "clock_offset_uncertainty_s": (
                        round(max(unc), 6) if unc else None),
                    "gang_skew_rows": skew_rows[-4:],
                }
            finally:
                _egang.set_form_timeout_s(old_form)
                gc3.stop()
                for w in workers:
                    w.stop()
                m.stop()

        try:
            _skew_d = _gang_skew_digest()
        except Exception as e:  # noqa: BLE001 — bench must not die on
            # the skew drill
            _skew_d = {"config": "gang_skew",
                       "error": f"{type(e).__name__}: {e}"}
        detail.append(_skew_d)

        # sharded gang digest (engine/gang.py sharded body): the
        # mesh-partitioned A/B — the SAME stencil bulk over a 2-host
        # gang, run replicated (gang_sharded=False: every member
        # evaluates all rows) then sharded (each member evaluates only
        # its shard_range; boundary rows ride the halo exchange) —
        # banking `gang_sharded_speedup` (better=higher): the ratio of
        # stage-phase rows/s, measured from the slowest member role's
        # gang.stage seconds, which excludes the fixed per-gang
        # rendezvous constant both modes pay identically.  Per-host
        # decode rows and halo bytes ride along as the
        # decode-isolation trajectory (each member should decode ~1/N
        # of the rows, not N x 1/1).
        def _gang_sharded_digest() -> dict:
            import struct as _struct
            from typing import Sequence as _Seq

            import numpy as _np

            from scanner_tpu import FrameType, Kernel, register_op
            from scanner_tpu.engine import gang as _egang
            from scanner_tpu.engine.service import Master, Worker

            def _pk(v: int) -> bytes:
                return _struct.pack("<q", v)

            def _tot(name: str) -> float:
                s = registry().snapshot().get(name, {})
                return sum(x["value"] for x in s.get("samples", []))

            @register_op(name="BenchShardStencil", stencil=[-1, 0])
            class BenchShardStencil(Kernel):
                def execute(self, frame: _Seq[FrameType]) -> bytes:
                    # heavy enough per row that eval dominates the
                    # per-task fixed costs: the A/B ratio should
                    # measure compute partitioning, not scheduler
                    # constants, and must clear the 1.6x gate with
                    # margin under ambient bench load
                    time.sleep(0.08)
                    return _pk(int(_np.asarray(frame,
                                               _np.int64).sum()))

            hdb = os.path.join(root, "gang_sharded_db")
            n_rows = 16
            hvid = os.path.join(root, "gang_sharded.mp4")
            scv.synthesize_video(hvid, num_frames=n_rows, width=64,
                                 height=48, fps=24, keyint=8)
            seedh = Client(db_path=hdb)
            seedh.ingest_videos([("gshard_vid", hvid)])
            m = Master(db_path=hdb, no_workers_timeout=60.0)
            addr = f"localhost:{m.port}"
            old_form = _egang.form_timeout_s()
            _egang.set_form_timeout_s(6.0)
            workers = [Worker(addr, db_path=hdb) for _ in range(2)]
            gc4 = Client(db_path=hdb, master=addr)

            def _stage_by_role() -> dict:
                fam = registry().snapshot().get(
                    "scanner_tpu_gang_phase_seconds_total", {})
                out: dict = {}
                for s in fam.get("samples", []):
                    if s["labels"].get("phase") == "stage":
                        out[s["labels"].get("role")] = s["value"]
                return out

            def _shards_by_role(name: str) -> dict:
                fam = registry().snapshot().get(name, {})
                return {s["labels"].get("role"): s["value"]
                        for s in fam.get("samples", [])}

            def _run_mode(mode: str, sharded: bool) -> dict:
                st0 = _stage_by_role()
                dr0 = _shards_by_role(
                    "scanner_tpu_gang_shard_decode_rows_total")
                hb0 = _tot("scanner_tpu_gang_shard_halo_bytes_total")
                col = gc4.io.Input(
                    [NamedVideoStream(gc4, "gshard_vid")])
                col = gc4.ops.BenchShardStencil(frame=col)
                out = NamedStream(gc4, f"gshard_{mode}")
                w0 = time.time()
                gc4.run(gc4.io.Output(col, [out]),
                        PerfParams.manual(4, 8, gang_hosts=2,
                                          gang_sharded=sharded),
                        cache_mode=CacheMode.Overwrite,
                        show_progress=False)
                wall = time.time() - w0
                rows = len(list(out.load()))
                st1 = _stage_by_role()
                stage_max = max(
                    (st1.get(r, 0.0) - st0.get(r, 0.0)
                     for r in st1), default=0.0)
                dr1 = _shards_by_role(
                    "scanner_tpu_gang_shard_decode_rows_total")
                return {
                    "mode": mode,
                    "rows_ok": rows == n_rows,
                    "wall_s": round(wall, 3),
                    "stage_s": round(stage_max, 3),
                    "stage_rows_per_s": (
                        round(rows / stage_max, 3)
                        if stage_max > 0 else None),
                    "decode_rows_by_member": {
                        r: dr1.get(r, 0.0) - dr0.get(r, 0.0)
                        for r in dr1},
                    "halo_bytes": _tot(
                        "scanner_tpu_gang_shard_halo_bytes_total")
                        - hb0,
                }

            try:
                rep = _run_mode("replicated", sharded=False)
                sha = _run_mode("sharded", sharded=True)
                speedup = None
                if rep["stage_rows_per_s"] and sha["stage_rows_per_s"]:
                    speedup = round(sha["stage_rows_per_s"]
                                    / rep["stage_rows_per_s"], 3)
                return {
                    "config": "gang_sharded",
                    "rows_ok": rep["rows_ok"] and sha["rows_ok"],
                    "error": None,
                    "replicated": rep,
                    "sharded": sha,
                    "gang_sharded_speedup": speedup,
                    "shard_commit_folds_ok": sum(
                        s["value"] for s in registry().snapshot().get(
                            "scanner_tpu_gang_shard_commit_folds_total",
                            {}).get("samples", [])
                        if s["labels"].get("result") == "ok"),
                }
            finally:
                _egang.set_form_timeout_s(old_form)
                gc4.stop()
                for w in workers:
                    w.stop()
                m.stop()
                seedh.stop()

        try:
            _shard_d = _gang_sharded_digest()
        except Exception as e:  # noqa: BLE001 — bench must not die on
            # the sharded drill
            _shard_d = {"config": "gang_sharded",
                        "error": f"{type(e).__name__}: {e}"}
        detail.append(_shard_d)

        # whole-pipeline fusion digest (graph/fusion.py, PERF.md §8):
        # the golden Resize->Blur->Histogram->HistDiff pipeline run
        # staged (SCANNER_TPU_FUSION semantics, fusion.set_enabled off)
        # then fused over the same clip.  Banked: the per-mode measured
        # op seconds (sum over members vs the one chain row), the
        # executables each mode minted, the intermediate HBM bytes the
        # fused program never materialized, and the direction-gated
        # fused_chain_speedup = staged op-seconds / fused chain-seconds
        def _fusion_digest() -> dict:
            from scanner_tpu.graph import fusion as _fusion

            members = ("Resize", "Blur", "Histogram", "HistDiff")
            # HistDiff (windowed, non-head) stays staged; the planner
            # forms the 3-member chain
            cid = "+".join(members[:3])

            def _by_op(name: str) -> dict:
                out: dict = {}
                for s in registry().snapshot().get(
                        name, {}).get("samples", []):
                    k = s["labels"].get("op", "_")
                    out[k] = out.get(k, 0.0) + s["value"]
                return out

            fdb = os.path.join(root, "fusion_db")
            n_rows = 96
            fvid = os.path.join(root, "fusion.mp4")
            scv.synthesize_video(fvid, num_frames=n_rows, width=W,
                                 height=H, fps=24, keyint=24)
            fc5 = Client(db_path=fdb)
            fc5.ingest_videos([("fz_vid", fvid)])
            keys = (cid,) + members

            def _run_mode(mode: str, on: bool) -> dict:
                prev = _fusion.enabled()
                _fusion.set_enabled(on)
                try:
                    s0 = _by_op("scanner_tpu_op_seconds_total")
                    r0 = _by_op("scanner_tpu_op_recompiles_total")
                    col = fc5.io.Input(
                        [NamedVideoStream(fc5, "fz_vid")])
                    col = fc5.ops.Resize(frame=col, width=[W // 2],
                                         height=[H // 2])
                    col = fc5.ops.Blur(frame=col, kernel_size=3,
                                       sigma=1.1)
                    col = fc5.ops.Histogram(frame=col)
                    col = fc5.ops.HistDiff(frame=col)
                    out = NamedStream(fc5, f"fz_{mode}")
                    w0 = time.time()
                    fc5.run(fc5.io.Output(col, [out]),
                            PerfParams.manual(8, 16),
                            cache_mode=CacheMode.Overwrite,
                            show_progress=False)
                    wall = time.time() - w0
                    rows = len(list(out.load()))
                    s1 = _by_op("scanner_tpu_op_seconds_total")
                    r1 = _by_op("scanner_tpu_op_recompiles_total")
                    return {
                        "mode": mode,
                        "rows_ok": rows == n_rows,
                        "wall_s": round(wall, 3),
                        "op_seconds": round(
                            sum(s1.get(k, 0.0) - s0.get(k, 0.0)
                                for k in keys), 4),
                        "executables_minted": int(
                            sum(r1.get(k, 0) - r0.get(k, 0)
                                for k in keys)),
                    }
                finally:
                    _fusion.set_enabled(prev)

            try:
                # cold pass per mode mints the executables; the banked
                # speedup comes from a second, warm pass so one-off
                # trace/compile time doesn't swamp the steady-state A/B
                staged = _run_mode("staged", on=False)
                fused = _run_mode("fused", on=True)
                staged_w = _run_mode("staged_warm", on=False)
                fused_w = _run_mode("fused_warm", on=True)
                speedup = None
                if staged_w["op_seconds"] and fused_w["op_seconds"]:
                    speedup = round(staged_w["op_seconds"]
                                    / fused_w["op_seconds"], 3)
                snap_f = registry().snapshot()
                saved = sum(
                    s["value"] for s in snap_f.get(
                        "scanner_tpu_fusion_intermediate_bytes_saved_"
                        "total", {}).get("samples", [])
                    if s["labels"].get("chain") == cid)
                chains = {
                    s["labels"]["chain"]: s["value"]
                    for s in snap_f.get(
                        "scanner_tpu_fusion_chains_planned",
                        {}).get("samples", [])}
                return {
                    "config": "fusion",
                    "rows_ok": (staged["rows_ok"] and fused["rows_ok"]
                                and staged_w["rows_ok"]
                                and fused_w["rows_ok"]),
                    "error": None,
                    "chain": cid,
                    "chains_planned": chains,
                    "staged": staged,
                    "fused": fused,
                    "staged_warm": staged_w,
                    "fused_warm": fused_w,
                    "fused_chain_speedup": speedup,
                    "executables_avoided":
                        staged["executables_minted"]
                        - fused["executables_minted"],
                    "intermediate_bytes_saved": saved,
                }
            finally:
                fc5.stop()

        try:
            _fz_d = _fusion_digest()
        except Exception as e:  # noqa: BLE001 — bench must not die on
            # the fusion A/B
            _fz_d = {"config": "fusion",
                     "error": f"{type(e).__name__}: {e}"}
        detail.append(_fz_d)

        # control-plane digest (engine/shardmap.py): a bounded live
        # sharded-master drill — two in-process shard masters, one
        # multiplexing worker.  Admission is probed per shard (NewJob
        # wall time; p99 = worst probe on the worst shard), then a
        # bulk owned by the NON-dialed shard is killed mid-flight
        # (checkpoint_frequency=0: journal-only durability) and a
        # successor started on the same port — banking shard-failover
        # recovery seconds and the FinishedWork coalescing yield so
        # tools/bench_history.py gates the sharded control plane like
        # any other metric
        def _control_plane_digest() -> dict:
            import socket as _socket
            import struct as _struct

            import cloudpickle as _cp

            from scanner_tpu import Kernel, register_op
            from scanner_tpu.engine import shardmap as _shmap
            from scanner_tpu.engine.service import Master, Worker

            def _pk(v: int) -> bytes:
                return _struct.pack("<q", v)

            def _tot(name: str, method: str = None) -> float:
                s = registry().snapshot().get(name, {})
                return sum(
                    x["value"] for x in s.get("samples", [])
                    if method is None
                    or x.get("labels", {}).get("method") == method)

            @register_op(name="BenchCpFast")
            class BenchCpFast(Kernel):
                def execute(self, x: bytes) -> bytes:
                    return _pk(2 * _struct.unpack("<q", x)[0])

            @register_op(name="BenchCpSlow")
            class BenchCpSlow(Kernel):
                # slow enough that the bulk outlives the mid-bulk
                # shard kill
                def execute(self, x: bytes) -> bytes:
                    time.sleep(0.15)
                    return _pk(3 * _struct.unpack("<q", x)[0])

            cdb = os.path.join(root, "cp_db")
            n_rows = 48
            os.environ["SCANNER_TPU_CONTROL_SHARDS"] = "2"
            _shmap.set_num_shards(2)
            seedc = Client(db_path=cdb)
            seedc.new_table("cp_src", ["output"],
                            [[_pk(100 + i)] for i in range(n_rows)])
            # spec blobs come from FRESH clients so each admission
            # sees the master-created tables of the previous one
            # (client-side table-id allocation is single-writer);
            # each client stays alive until its bulk drains
            spec_clients: list = []

            def _spec(op: str, out_name: str, **perf_kw) -> bytes:
                c = Client(db_path=cdb)
                spec_clients.append(c)
                col = c.io.Input([NamedStream(c, "cp_src")])
                col = getattr(c.ops, op)(x=col)
                node = c.io.Output(col, [NamedStream(c, out_name)])
                return _cp.dumps({
                    "outputs": [node],
                    "perf": PerfParams.manual(2, 2, **perf_kw),
                    "cache_mode": CacheMode.Overwrite.value})

            ports = []
            for _ in range(2):
                with _socket.socket() as s:
                    s.bind(("localhost", 0))
                    ports.append(s.getsockname()[1])
            masters = [Master(db_path=cdb, port=ports[k], shard_id=k,
                              num_shards=2, no_workers_timeout=60.0)
                       for k in range(2)]
            worker = Worker(f"localhost:{ports[0]}", db_path=cdb)
            successor = None
            coal_fw0 = _tot("scanner_tpu_rpc_coalesced_total",
                            "FinishedWork")

            def _drain(m, bulk_id: int, timeout_s: float) -> dict:
                end = time.time() + timeout_s
                st: dict = {}
                while time.time() < end:
                    st = m._rpc_job_status({"bulk_id": bulk_id})
                    if st.get("finished"):
                        return st
                    time.sleep(0.1)
                return st

            try:
                deadline = time.time() + 30
                while time.time() < deadline \
                        and len(worker._links) < 2:
                    time.sleep(0.05)
                if len(worker._links) < 2:
                    return {"config": "control_plane",
                            "error": "worker never linked both shards"}
                # admission probes, sequential per shard (the serial
                # admission path is what the p99 judges)
                tasks_done = 0.0
                admit: list = []
                for sid in range(2):
                    for i in range(3):
                        blob = _spec("BenchCpFast",
                                     f"cp_probe_{sid}_{i}")
                        t0 = time.time()
                        r = masters[sid]._rpc_new_job(
                            {"spec": blob,
                             "token": f"cp-probe-{sid}-{i}"})
                        admit.append(time.time() - t0)
                        if "bulk_id" not in r:
                            return {"config": "control_plane",
                                    "error": f"admission NACK: {r}"}
                        st = _drain(masters[sid], r["bulk_id"], 60)
                        if not st.get("finished"):
                            return {
                                "config": "control_plane",
                                "error": f"probe bulk stuck on shard "
                                         f"{sid}: {st.get('error')}"}
                        tasks_done += st.get("tasks_done") or 0
                # shard failover: the job lands on shard 1 — the
                # NON-dialed shard, so recovery also proves the
                # worker's multiplexed link redials the successor
                blob = _spec("BenchCpSlow", "cp_fo_out",
                             checkpoint_frequency=0)
                r = masters[1]._rpc_new_job(
                    {"spec": blob, "token": "cp-fo"})
                if "bulk_id" not in r:
                    return {"config": "control_plane",
                            "error": f"failover admission NACK: {r}"}
                bulk_id = r["bulk_id"]
                end = time.time() + 60
                done_at_kill = 0
                while time.time() < end:
                    st = masters[1]._rpc_job_status(
                        {"bulk_id": bulk_id})
                    if (st.get("tasks_done") or 0) >= 4:
                        done_at_kill = st["tasks_done"]
                        break
                    time.sleep(0.05)
                masters[1].stop()  # abrupt: bulk active, no cleanup
                kill_at = time.time()
                for _ in range(20):
                    try:
                        successor = Master(
                            db_path=cdb, port=ports[1], shard_id=1,
                            num_shards=2, no_workers_timeout=60.0)
                        break
                    except Exception:  # noqa: BLE001 — port lingering
                        time.sleep(0.25)
                if successor is None:
                    return {"config": "control_plane",
                            "error": "successor never bound the port"}
                st = _drain(successor, bulk_id, 120)
                recovery = round(time.time() - kill_at, 3) \
                    if st.get("finished") else None
                tasks_done += st.get("tasks_done") or 0
                rows = None
                vc = Client(db_path=cdb)
                try:
                    rows = len(list(
                        NamedStream(vc, "cp_fo_out").load()))
                finally:
                    vc.stop()
                coal_fw = _tot("scanner_tpu_rpc_coalesced_total",
                               "FinishedWork") - coal_fw0
                return {
                    "config": "control_plane",
                    "rows_ok": rows == n_rows,
                    "done_at_kill": done_at_kill,
                    "per_shard_admission_p99_s": round(max(admit), 4),
                    "shard_failover_recovery_s": recovery,
                    "shard_failovers": _tot(
                        "scanner_tpu_shard_failovers_total"),
                    "shard_journal_reexec": _tot(
                        "scanner_tpu_shard_journal_reexec_total"),
                    "finished_coalesced": coal_fw,
                    "finished_coalescing_ratio": round(
                        coal_fw / tasks_done, 4)
                        if tasks_done else None,
                }
            finally:
                for obj in ([worker] + masters
                            + ([successor] if successor else [])
                            + spec_clients + [seedc]):
                    try:
                        obj.stop()
                    except Exception:  # noqa: BLE001 — teardown of an
                        pass           # already-stopped shard
                os.environ.pop("SCANNER_TPU_CONTROL_SHARDS", None)
                _shmap.set_num_shards(1)

        try:
            _cp_d = _control_plane_digest()
        except Exception as e:  # noqa: BLE001 — bench must not die on
            # the control-plane drill
            _cp_d = {"config": "control_plane",
                     "error": f"{type(e).__name__}: {e}"}
        detail.append(_cp_d)
        # stable per-direction baseline keys (ROADMAP "bank per-item
        # baselines for the new directions"): one flat entry with a
        # declared better= direction per metric, so
        # tools/bench_history.py can gate the serving (task-latency
        # p99), cache (compile-cache hit rate) and scan/kernel (per-op
        # efficiency) directions from the first round that banks a
        # baseline (bench_history.py --write-baselines).  The mean is
        # WEIGHTED by measured seconds: an unweighted mean over
        # whichever (op, device, bucket) rows a round happened to hit
        # would swing on a rarely-run tail bucket's noisy sample and
        # trip the gate with no real change.
        _eff_w = sum(o["seconds"] for o in _eff_ops
                     if o.get("efficiency") is not None)
        _eff_mean = (round(sum(o["efficiency"] * o["seconds"]
                               for o in _eff_ops
                               if o.get("efficiency") is not None)
                           / _eff_w, 6) if _eff_w else None)
        detail.append({
            "config": "baseline_metrics",
            "metrics": {
                "task_latency_p99_s": {
                    "value": _tlq.get("p99_s"), "better": "lower"},
                "op_efficiency_mean": {
                    "value": _eff_mean, "better": "higher"},
                "compile_cache_hit_rate": {
                    "value": _csum.get("cache_hit_rate"),
                    "better": "higher"},
                "frame_cache_hit_rate": {
                    "value": _fc_d.get("warm_hit_rate"),
                    "better": "higher"},
                "frame_cache_decode_seconds_saved": {
                    "value": _fc_d.get("decode_seconds_saved"),
                    "better": "higher"},
                "frame_cache_h2d_bytes_saved": {
                    "value": _fc_d.get("h2d_bytes_saved"),
                    "better": "higher"},
                "preemption_recovery_s": {
                    "value": _rem_d.get("preemption_recovery_s"),
                    "better": "lower"},
                "failover_recovery_s": {
                    "value": _fo_d.get("failover_recovery_s"),
                    "better": "lower"},
                "tasks_lost_on_recovery": {
                    "value": _fo_d.get("tasks_lost_on_recovery"),
                    "better": "lower"},
                "gang_reform_s": {
                    "value": _gang_d.get("gang_reform_s"),
                    "better": "lower"},
                "gang_barrier_skew_p99_s": {
                    "value": _skew_d.get("gang_barrier_skew_p99_s"),
                    "better": "lower"},
                "clock_offset_uncertainty_s": {
                    "value": _skew_d.get("clock_offset_uncertainty_s"),
                    "better": "lower"},
                "gang_sharded_speedup": {
                    "value": _shard_d.get("gang_sharded_speedup"),
                    "better": "higher"},
                "fused_chain_speedup": {
                    "value": _fz_d.get("fused_chain_speedup"),
                    "better": "higher"},
                "shard_failover_recovery_s": {
                    "value": _cp_d.get("shard_failover_recovery_s"),
                    "better": "lower"},
                "per_shard_admission_p99_s": {
                    "value": _cp_d.get("per_shard_admission_p99_s"),
                    "better": "lower"},
            },
        })
        # health digest (util/health.py): alert transitions fired during
        # this bench run plus the latency-quantile snapshot the SLO
        # rules judge — tools/bench_history.py reads this trajectory so
        # a round that alerted is visible next to its fps
        from scanner_tpu.util import health as _health
        _alert_transitions: dict = {}
        for s in snap.get("scanner_tpu_alerts_transitions_total",
                          {}).get("samples", []):
            lbl = s.get("labels", {})
            key = f"{lbl.get('rule', '?')}:{lbl.get('state', '?')}"
            _alert_transitions[key] = _alert_transitions.get(key, 0.0) \
                + s.get("value", 0.0)
        _hstat = _health.status_dict()
        detail.append({
            "config": "health",
            "status": _hstat.get("status"),
            "reasons": _hstat.get("reasons"),
            "firing": _hstat.get("firing"),
            "alert_transitions": _alert_transitions,
            "task_latency":
                hist_quantiles("scanner_tpu_task_latency_seconds"),
            "rpc_latency":
                hist_quantiles("scanner_tpu_rpc_latency_seconds"),
        })
        detail.append({"config": "metrics_registry", "snapshot": snap})
        # static-analysis digest: finding counts per code ride with every
        # perf round, so analyzer drift (new findings, baseline growth)
        # is visible in the same trajectory as fps regressions
        try:
            from scanner_tpu.analysis.static import (
                analyze, load_baseline, split_findings)
            _root = os.path.dirname(os.path.abspath(__file__))
            _sc_t0 = time.perf_counter()
            _proj, _found = analyze(
                [os.path.join(_root, "scanner_tpu")], root=_root)
            _sc_s = round(time.perf_counter() - _sc_t0, 3)
            _res = split_findings(_proj, _found, load_baseline(
                os.path.join(_root, "tools",
                             "scanner_check_baseline.json")))
            _counts: dict = {}
            for _f in _found:
                _counts[_f.code] = _counts.get(_f.code, 0) + 1
            detail.append({
                "config": "static_analysis",
                "findings_by_code": _counts,
                "unsuppressed": len(_res.unsuppressed),
                "baselined": len(_res.baselined),
                "inline_suppressed": len(_res.inline_suppressed),
                "files_analyzed": len(_proj.modules),
                "scanner_check_seconds": _sc_s,
            })
            # direction-gated wall clock for the full four-family run
            # over ONE shared Project — the analyzer's perf budget is
            # banked and regression-gated like any serving metric
            # (tools/bench_history.py --write-baselines)
            for _d in detail:
                if _d.get("config") == "baseline_metrics":
                    _d["metrics"]["scanner_check_seconds"] = {
                        "value": _sc_s, "better": "lower"}
        except Exception as e:  # noqa: BLE001 — bench must not die on lint
            detail.append({"config": "static_analysis",
                           "error": f"{type(e).__name__}: {e}"})
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_DETAIL.json"), "w") as f:
            json.dump(detail, f, indent=1)

        by_cfg = {d["config"]: d["fps"] for d in detail if "fps" in d}
        if 1 in by_cfg and 3 in by_cfg:
            value = round((by_cfg[1] + by_cfg[3]) / 2.0, 2)
            metric = "histogram+pose_pipeline_throughput"
        else:
            value = detail[0]["fps"]
            metric = f"config{detail[0]['config']}_pipeline_throughput"
        print(json.dumps({
            "metric": metric,
            "value": value,
            "unit": "frames/sec/chip",
            "vs_baseline": round(value / BASELINE_FPS, 4),
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
