"""Reverse image search app: find where a query frame appears in a video
by comparing per-frame color histograms.  (Reference:
examples/apps/reverse_image_search.)

Usage: python examples/reverse_image_search.py path/to/video.mp4 [db_path]
With no query image the clip's middle frame is used as the query and the
app asserts it finds itself (and its temporal neighborhood) first.
"""

import sys

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels  # registers Histogram


def hist_of_image(img: np.ndarray) -> np.ndarray:
    """(H, W, 3) uint8 -> (3, 16) per-channel histogram, matching the
    Histogram op's binning."""
    return np.stack([
        np.bincount((img[..., c].ravel() >> 4), minlength=16)
        for c in range(3)]).astype(np.int32)


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)

    movie = NamedVideoStream(sc, "search-clip", path=video_path)
    frames = sc.io.Input([movie])
    hists = sc.ops.Histogram(frame=frames)
    out = NamedStream(sc, "search-hists")
    sc.run(sc.io.Output(hists, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)
    table = np.stack(list(out.load())).astype(np.float64)  # (N, 3, 16)

    # query: the middle frame, read back through the client frame reader
    n = len(table)
    query_idx = n // 2
    query = sc.load_frames("search-clip", [query_idx])[0]
    qh = hist_of_image(query).astype(np.float64)

    # chi-squared distance, smaller = more similar
    denom = table + qh[None] + 1e-9
    dist = ((table - qh[None]) ** 2 / denom).sum(axis=(1, 2))
    ranked = np.argsort(dist)
    top = ranked[:5]
    print("query frame:", query_idx)
    print("best matches:", top.tolist(), "distances:",
          [round(float(dist[i]), 2) for i in top])
    assert top[0] == query_idx, \
        f"query frame should match itself first (got {top[0]})"


if __name__ == "__main__":
    main()
