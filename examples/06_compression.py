"""Tutorial 06: output compression (reference tutorials/06_compression.py).

Frame outputs re-encode to H.264 by default; .lossless() / .compress()
tune it, save_mp4 exports a playable file without re-encoding.
"""

import sys

from scanner_tpu import (CacheMode, Client, NamedVideoStream, PerfParams)
import scanner_tpu.kernels


def main():
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    movie = NamedVideoStream(sc, "t06", path=sys.argv[1])
    frames = sc.io.Input([movie])
    small = sc.ops.Resize(frame=frames, width=[320], height=[240])
    out = NamedVideoStream(sc, "t06_small")
    sc.run(sc.io.Output(small.compress("video", crf=28), [out]),
           PerfParams.estimate(), cache_mode=CacheMode.Overwrite)
    out.save_mp4("/tmp/t06_small.mp4")
    print("wrote /tmp/t06_small.mp4")


if __name__ == "__main__":
    main()
