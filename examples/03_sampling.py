"""Tutorial 03: stream sampling (reference tutorials/03_sampling.py).

Samplers select which rows flow downstream; the engine decodes ONLY the
frames the sampled rows (plus stencils) require, seeking keyframe-exact.
"""

import sys

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels


def main():
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    movie = NamedVideoStream(sc, "t03", path=sys.argv[1])
    frames = sc.io.Input([movie])

    strided = sc.streams.Stride(frames, [{"stride": 10}])   # every 10th
    # other samplers:
    #   sc.streams.Range(frames, [(30, 60)])
    #   sc.streams.Gather(frames, [[0, 99, 500]])
    #   sc.streams.StridedRanges(frames, [[(0, 100), (500, 600)]], stride=5)

    hist = sc.ops.Histogram(frame=strided)
    out = NamedStream(sc, "t03_hists")
    job = sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
                 cache_mode=CacheMode.Overwrite)
    print(f"{out.len()} histograms from every 10th frame")


if __name__ == "__main__":
    main()
