"""Tutorial 07: profiling (reference tutorials/07_profiling.py).

Every job records per-stage intervals; write_trace emits Chrome trace JSON
(chrome://tracing or ui.perfetto.dev).
"""

import sys

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels


def main():
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    movie = NamedVideoStream(sc, "t07", path=sys.argv[1])
    frames = sc.io.Input([movie])
    hist = sc.ops.Histogram(frame=frames)
    out = NamedStream(sc, "t07_hists")
    job_id = sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
                    cache_mode=CacheMode.Overwrite)
    profile = sc.get_profile(job_id)
    profile.write_trace("/tmp/t07.trace.json")
    for name, s in profile.statistics().items():
        print(name, s)
    print("trace: /tmp/t07.trace.json")


if __name__ == "__main__":
    main()
