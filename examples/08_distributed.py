"""Tutorial 08: running on a cluster (reference master/worker bring-up).

Start a master and workers (here: same machine; in production one worker
per TPU host — see scanner_tpu/deploy.py for GKE manifests), then point a
Client at the master: the API is unchanged.
"""

import sys

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
from scanner_tpu.engine.service import Master, Worker
import scanner_tpu.kernels


def main():
    db = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    master = Master(db_path=db)
    addr = f"localhost:{master.port}"
    workers = [Worker(addr, db_path=db) for _ in range(2)]

    sc = Client(db_path=db, master=addr)
    movie = NamedVideoStream(sc, "t08", path=sys.argv[1])
    movie.ensure_ingested()
    frames = sc.io.Input([movie])
    hist = sc.ops.Histogram(frame=frames)
    out = NamedStream(sc, "t08_hists")
    sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)
    print(f"{out.len()} rows computed by {len(workers)} workers")
    sc.stop()
    for w in workers:
        w.stop()
    master.stop()


if __name__ == "__main__":
    main()
