"""Hyperlapse app: render a smooth timelapse by *selecting* frames, not
just striding.  (Reference: examples/apps/hyperlapse — real-time
hyperlapse via optimal frame selection.)

Two engine passes:
1. Histogram over the whole clip (device op) -> per-frame signatures.
2. Dynamic programming on the host picks a frame path with target
   speedup v: successive gaps stay in [v-w, v+w] while minimizing visual
   jumps (chi-squared histogram distance) — smoother than a fixed
   Stride when content moves unevenly.
3. A Gather graph decodes exactly the chosen frames (keyframe-indexed
   minimal decode) and writes the hyperlapse as a new video stream.

Usage: python examples/hyperlapse.py path/to/video.mp4 [db_path] [speedup]
"""

import sys

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels  # registers Histogram


def chi2(a: np.ndarray, b: np.ndarray) -> float:
    return float(((a - b) ** 2 / (a + b + 1e-9)).sum())


def select_path(hists: np.ndarray, speedup: int, window: int = 2
                ) -> list:
    """DP over frames: cost(i->j) = chi2(hist_i, hist_j) + a quadratic
    penalty for deviating from the target gap.  Returns the chosen frame
    indices (starting at 0)."""
    n = len(hists)
    gaps = [g for g in range(max(1, speedup - window),
                             speedup + window + 1)]
    scale = np.maximum(hists.sum(axis=(1, 2)).mean(), 1.0)
    best = np.full(n, np.inf)
    prev = np.full(n, -1, np.int64)
    best[0] = 0.0
    for i in range(n):
        if not np.isfinite(best[i]):
            continue
        for g in gaps:
            j = i + g
            if j >= n:
                continue
            c = chi2(hists[i], hists[j]) / scale \
                + 0.05 * (g - speedup) ** 2
            if best[i] + c < best[j]:
                best[j] = best[i] + c
                prev[j] = i
    # best endpoint in the final gap window that was actually reached by
    # at least one hop (frame 0 alone is not a timelapse)
    tail = np.arange(max(0, n - speedup - window), n)
    reached = tail[np.isfinite(best[tail]) & (prev[tail] >= 0)]
    if len(reached) == 0:
        raise ValueError(
            f"speedup {speedup} too large for a {n}-frame clip "
            f"(no frame within the final gap window is reachable)")
    end = reached[np.argmin(best[reached])]
    path = []
    i = int(end)
    while i >= 0:
        path.append(i)
        i = int(prev[i])
    return path[::-1]


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    speedup = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    sc = Client(db_path=db_path)

    movie = NamedVideoStream(sc, "lapse-clip", path=video_path)

    # pass 1: per-frame signatures
    frames = sc.io.Input([movie])
    hists = sc.ops.Histogram(frame=frames)
    sig = NamedStream(sc, "lapse-hists")
    sc.run(sc.io.Output(hists, [sig]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)
    table = np.stack(list(sig.load())).astype(np.float64)

    # pass 2: DP selection on the host
    path = select_path(table, speedup)
    gaps = np.diff(path)
    print(f"{len(table)} frames -> {len(path)} selected "
          f"(target gap {speedup}, actual mean {gaps.mean():.2f}, "
          f"range [{gaps.min()}, {gaps.max()}])")
    assert (gaps >= 1).all()

    # pass 3: decode exactly the chosen frames, write the hyperlapse
    frames = sc.io.Input([movie])
    picked = sc.streams.Gather(frames, [path])
    out = NamedVideoStream(sc, "lapse-out")
    sc.run(sc.io.Output(picked, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)
    mp4 = db_path.rstrip("/") + "_hyperlapse.mp4"
    out.save_mp4(mp4)
    assert out.len() == len(path)
    print(f"wrote {out.len()} frames -> {mp4}")


if __name__ == "__main__":
    main()
