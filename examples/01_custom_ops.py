"""Tutorial 01: defining your own ops (reference tutorials/01+02).

Ops are Python classes (usually wrapping jitted JAX fns) registered with
@register_op; input/output columns come from type annotations.
"""

import sys
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from scanner_tpu import (CacheMode, Client, DeviceType, FrameType, Kernel,
                         NamedStream, NamedVideoStream, PerfParams,
                         register_op)


@register_op(device=DeviceType.TPU, batch=16)
class Brightness(Kernel):
    """Mean luma per frame, batched through one jitted XLA program."""

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        frames = jnp.asarray(np.asarray(frame), jnp.float32)
        w = jnp.asarray([0.299, 0.587, 0.114])
        return [float(x) for x in (frames * w).sum(-1).mean((1, 2))]


@register_op(stencil=[-1, 0, 1])
class TemporalMedian(Kernel):
    """3-frame temporal median — a stencil op: the engine hands each call
    the [-1, 0, +1] window, decoding exactly the needed extra frames."""

    def execute(self, frame: Sequence[FrameType]) -> FrameType:
        return np.median(np.stack(frame), axis=0).astype(np.uint8)


def main():
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    movie = NamedVideoStream(sc, "t01", path=sys.argv[1])
    frames = sc.io.Input([movie])
    bright = sc.ops.Brightness(frame=frames)
    out = NamedStream(sc, "t01_brightness")
    sc.run(sc.io.Output(bright, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)
    vals = list(out.load())
    print(f"brightness: min {min(vals):.1f} max {max(vals):.1f}")


if __name__ == "__main__":
    main()
