"""Walkthrough: stride-sample a video, resize, convert to grayscale with a
custom per-frame op, and export the result as an mp4.  (Reference:
examples/apps/walkthroughs/grayscale_conversion.py.)

Usage: python examples/grayscale_conversion.py path/to/video.mp4 [db_path]
"""

import sys

import numpy as np

from scanner_tpu import (CacheMode, Client, FrameType, NamedStream,
                         NamedVideoStream, PerfParams, register_op)
import scanner_tpu.kernels  # registers the stdlib ops (Resize, Grayscale)


@register_op()
def CloneChannels(config, frame: FrameType, replications=3) -> FrameType:
    """Replicate a (possibly single-channel) frame into N channels —
    the walkthrough's custom-op teaching point."""
    f = np.asarray(frame)
    if f.ndim == 3:
        f = f[..., 0]
    return np.dstack([f] * replications)


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)

    movie = NamedVideoStream(sc, "walkthrough-clip", path=video_path)
    frames = sc.io.Input([movie])
    sampled = sc.streams.Stride(frames, [{"stride": 2}])
    resized = sc.ops.Resize(frame=sampled, width=[64], height=[48])
    gray = sc.ops.Grayscale(frame=resized)
    gray3 = sc.ops.CloneChannels(frame=gray, replications=3)

    out = NamedVideoStream(sc, "walkthrough-grayscale")
    sc.run(sc.io.Output(gray3, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)

    mp4_path = db_path.rstrip("/") + "_grayscale.mp4"
    out.save_mp4(mp4_path)
    n = out.len()
    print(f"wrote {n} grayscale frames -> {mp4_path}")
    rows = list(out.load())
    assert len(rows) == n
    # grayscale: all three channels equal
    assert np.array_equal(rows[0][..., 0], rows[0][..., 1])


if __name__ == "__main__":
    main()
