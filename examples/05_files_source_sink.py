"""Tutorial 05: pluggable sources/sinks (reference tutorials/05 +
scannertools FilesStream).

Any CustomStorage subclass can feed or receive a graph; FilesStream stores
one file per row.
"""

import os
import sys

from scanner_tpu import CacheMode, Client, NamedVideoStream, PerfParams
from scanner_tpu.storage import FilesStream
import scanner_tpu.kernels


def main():
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    movie = NamedVideoStream(sc, "t05", path=sys.argv[1])
    frames = sc.io.Input([movie])
    sampled = sc.streams.Stride(frames, [{"stride": 30}])
    pngs = sc.ops.ImageEncode(frame=sampled, format="png")
    out = FilesStream("thumbs", "/tmp/scanner_tpu_thumbs", ext="png")
    sc.run(sc.io.Output(pngs, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)
    print(f"wrote {out.len()} thumbnails under /tmp/scanner_tpu_thumbs/thumbs")


if __name__ == "__main__":
    main()
