"""Face detection app: per-frame face boxes over a video, using the
shipped trained weights.  (Reference: examples/apps/face_detection/main.py,
which runs an externally-trained face detector; these weights come from
scanner_tpu.models.detect_train's synthetic face-scene task.)

Usage: python examples/face_detection.py [path/to/video.mp4] [stride]
With no video argument a synthetic face-scene clip is generated and the
reported boxes are scored (recall/IoU) against the ground truth.
"""

import os
import sys
import tempfile

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.models  # registers FaceDetect
from scanner_tpu.models import unpack_detections
from scanner_tpu.models.detect_train import (WIDTH, box_iou,
                                             render_face_scene,
                                             synth_scene_video)


def main():
    video_path = sys.argv[1] if len(sys.argv) > 1 else None
    stride = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    truth = None
    if video_path is None:
        video_path = os.path.join(tempfile.mkdtemp(prefix="facedet_ex_"),
                                  "faces.mp4")
        truth = synth_scene_video(video_path, renderer=render_face_scene,
                                  num_frames=16)

    sc = Client(db_path=os.path.join(
        tempfile.mkdtemp(prefix="facedet_db_"), "db"))
    try:
        movie = NamedVideoStream(sc, "facedet_movie", path=video_path)
        frames = sc.io.Input([movie])
        sampled = sc.streams.Stride(frames, [{"stride": stride}])
        # width 8 restores the shipped trained face weights by default
        dets = sc.ops.FaceDetect(frame=sampled, width=WIDTH,
                                 score_thresh=0.3)
        out = NamedStream(sc, "face_detections")
        sc.run(sc.io.Output(dets, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite)

        hits = total = 0
        for i, det in enumerate(out.load()):
            d = unpack_detections(det)
            boxes, scores = d["boxes"], d["scores"]
            if i < 5:
                tops = ", ".join(
                    f"[{b[0]:.2f} {b[1]:.2f} {b[2]:.2f} {b[3]:.2f}]@"
                    f"{s:.2f}" for b, s in zip(boxes[:3], scores[:3]))
                print(f"frame {i * stride}: {len(boxes)} faces  {tops}")
            if truth is not None:
                for gt in truth[i * stride]:
                    total += 1
                    if any(box_iou(gt, b) >= 0.3 for b in boxes):
                        hits += 1
        if truth is not None:
            print(f"recall@IoU0.3: {hits}/{total} "
                  f"({100.0 * hits / max(total, 1):.0f}%)")
            assert hits >= 0.7 * total, \
                "shipped face detector failed to localize the scenes"
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
