"""Re-identification feature extraction: detect → crop → embed.
(Reference: examples/apps/open-reid-feature-extraction/extract_features.py
— per-detection feature vectors over a video.)

Pipeline: ObjectDetect finds boxes per frame, TopBox picks the strongest
detection (full frame when none), CropResize extracts a fixed-size crop
on device, FaceEmbedding produces the L2-normalized feature vector.

Usage: python examples/reid_features.py path/to/video.mp4 [db_path]
"""

import sys
import tempfile
from typing import Any

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams, register_op)
import scanner_tpu.kernels  # CropResize
import scanner_tpu.models   # ObjectDetect, FaceEmbedding
from scanner_tpu.models import unpack_detections


@register_op()
def TopBox(config, det: Any) -> Any:
    """Strongest non-degenerate detection's box; the whole frame when
    nothing usable fired.  Border-clipped boxes can collapse to zero
    area — skip those, not legitimately small detections."""
    d = unpack_detections(det)
    order = np.argsort(d["scores"])[::-1]
    for i in order:
        b = np.asarray(d["boxes"][i], np.float32)
        if (b[2] - b[0]) * (b[3] - b[1]) > 1e-6:
            return b
    return np.asarray([0.0, 0.0, 1.0, 1.0], np.float32)


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else \
        tempfile.mkdtemp(prefix="reid_db_")
    sc = Client(db_path=db_path)
    try:
        movie = NamedVideoStream(sc, "reid_movie", path=video_path)
        frames = sc.io.Input([movie])
        # width 8 restores the shipped trained weights by default
        # (models/weights/, provenance models/detect_train.py)
        det = sc.ops.ObjectDetect(frame=frames, width=8)
        box = sc.ops.TopBox(det=det)
        crops = sc.ops.CropResize(frame=frames, box=box, size=64)
        feats = sc.ops.FaceEmbedding(frame=crops, width=8)
        out = NamedStream(sc, "reid_features")
        sc.run(sc.io.Output(feats, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite)
        rows = list(out.load())
        print(f"{len(rows)} feature vectors of dim {rows[0].shape[0]}; "
              f"|f| = {np.linalg.norm(rows[0]):.3f}")
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
