"""Tutorial 09: native (C) ops.

(Reference: examples/tutorials/08_defining_cpp_ops.py + 09/10 — the C++
extension API compiled into a shared library and loaded with load_op.)

This framework's native extension path is a C library driven from an
in-process Python kernel via ctypes — the same pattern the built-in video
layer uses (scanner_tpu/video/lib.py wrapping cpp/scvid.cpp).  The C side
releases the GIL implicitly (ctypes calls drop it), so native kernels
running in the engine's evaluator threads actually overlap.

The example builds a tiny C "temporal difference" op at runtime with g++,
wraps it in a batched Kernel, and runs it in a graph next to the JAX
stdlib ops.  In a real extension you would ship the .so and register the
kernel from your package; `Client.load_op` can load such a module
remotely (cloudpickled, tutorial 01).

Usage: python examples/09_native_ops.py [path/to/video.mp4] [db_path]
"""

import ctypes
import os
import subprocess
import sys
import tempfile
from typing import Any, Sequence

import numpy as np

from scanner_tpu import (CacheMode, Client, FrameType, Kernel, NamedStream,
                        NamedVideoStream, PerfParams, register_op)

C_SRC = r"""
#include <stdint.h>
#include <stdlib.h>

// mean absolute difference between consecutive frames of a batch;
// out[i] = mad(frame[i], frame[i-1]), out[0] = 0 for the batch head.
// extern "C": g++ builds this, ctypes needs the unmangled symbol.
extern "C" __attribute__((visibility("default")))
void frame_mad(const uint8_t* frames, int64_t n, int64_t hw3,
               double* out) {
  out[0] = 0.0;
  for (int64_t i = 1; i < n; ++i) {
    const uint8_t* a = frames + (i - 1) * hw3;
    const uint8_t* b = frames + i * hw3;
    int64_t acc = 0;
    for (int64_t p = 0; p < hw3; ++p)
      acc += labs((long)b[p] - (long)a[p]);
    out[i] = (double)acc / (double)hw3;
  }
}
"""


def build_native_lib(workdir: str) -> str:
    """Compile the C op to a shared library (a real extension ships the
    .so; building at runtime keeps the tutorial self-contained)."""
    src = os.path.join(workdir, "frame_mad.c")
    lib = os.path.join(workdir, "libframe_mad.so")
    with open(src, "w") as f:
        f.write(C_SRC)
    subprocess.run(["g++", "-O2", "-fPIC", "-shared", src, "-o", lib],
                   check=True)
    return lib


@register_op(name="NativeMAD", batch=16, stencil=[-1, 0])
class NativeMAD(Kernel):
    """Per-frame mean-absolute-difference vs the previous frame, computed
    in C.  The stencil [-1, 0] hands each row its predecessor, exactly
    like the reference's stenciled C++ ops (test_ops.cpp OpticalFlow)."""

    def __init__(self, config, lib_path: str = ""):
        super().__init__(config)
        self._lib = ctypes.CDLL(lib_path)
        self._lib.frame_mad.restype = None
        self._lib.frame_mad.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double)]

    def execute(self, frame: Sequence[Sequence[FrameType]]) -> Sequence[Any]:
        # frame: (batch, 2, H, W, 3) stencil windows [prev, cur]
        win = np.ascontiguousarray(np.asarray(frame, np.uint8))
        b = win.shape[0]
        hw3 = int(np.prod(win.shape[2:]))
        # rows alternate [prev0, cur0, prev1, cur1, ...] already
        prev_cur = win.reshape(b * 2, hw3)
        # one C call per row pair keeps the example simple; the C side
        # computes mad(prev, cur) as out[1] of each 2-frame run
        pair_out = np.zeros(2, np.float64)
        res = []
        for i in range(b):
            self._lib.frame_mad(
                prev_cur[2 * i:2 * i + 2].ctypes.data_as(ctypes.c_void_p),
                2, hw3, pair_out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_double)))
            res.append(float(pair_out[1]))
        return res


def main():
    from scanner_tpu import video as scv

    video_path = sys.argv[1] if len(sys.argv) > 1 else None
    workdir = tempfile.mkdtemp(prefix="native_op_")
    if video_path is None:
        video_path = os.path.join(workdir, "clip.mp4")
        scv.synthesize_video(video_path, num_frames=32, width=64,
                             height=48, fps=24)
    db_path = sys.argv[2] if len(sys.argv) > 2 else \
        os.path.join(workdir, "db")

    lib_path = build_native_lib(workdir)
    sc = Client(db_path=db_path)
    try:
        movie = NamedVideoStream(sc, "native_movie", path=video_path)
        frames = sc.io.Input([movie])
        mad = sc.ops.NativeMAD(frame=frames, lib_path=lib_path)
        out = NamedStream(sc, "native_mad")
        sc.run(sc.io.Output(mad, [out]), PerfParams.manual(8, 16),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        rows = list(out.load())
        print(f"{len(rows)} frame-difference values from the C op; "
              f"first five: {[round(r, 2) for r in rows[:5]]}")
        assert rows[0] == 0.0          # REPEAT_EDGE: row 0's prev = itself
        assert all(r >= 0 for r in rows)
        assert max(rows[1:]) > 0.5     # synthetic clip has motion
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
