"""Shot detection app: histogram-difference boundaries + montage export.
(Reference: examples/apps/shot_detection.)

Usage: python examples/shot_detection.py path/to/video.mp4
"""

import sys

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels
from scanner_tpu.kernels.shot import detect_shots
from scanner_tpu import video as scv


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    movie = NamedVideoStream(sc, "shots_movie", path=video_path)

    frames = sc.io.Input([movie])
    hists = sc.ops.Histogram(frame=frames)
    diffs = sc.ops.HistogramDelta(hist=hists)
    out = NamedStream(sc, "shot_diffs")
    sc.run(sc.io.Output(diffs, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)

    d = np.asarray(list(out.load()))
    boundaries = detect_shots(d)
    print(f"{len(boundaries)} shot boundaries: {boundaries.tolist()}")

    # decode exactly one keyframe-exact frame per shot (minimal decode)
    if len(boundaries):
        reps = sc.load_frames("shots_movie", boundaries.tolist())
        print("representative frames:", reps.shape)


if __name__ == "__main__":
    main()
