"""Pose detection app: per-frame keypoints over a sampled stream.
(Reference: examples/apps/pose_detection/main.py.)

Usage: python examples/pose_detection.py path/to/video.mp4 [stride]
"""

import sys

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.models  # registers PoseDetect


def main():
    video_path = sys.argv[1]
    stride = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    sc = Client(db_path="/tmp/scanner_tpu_db")
    movie = NamedVideoStream(sc, "pose_movie", path=video_path)

    frames = sc.io.Input([movie])
    sampled = sc.streams.Stride(frames, [{"stride": stride}])
    poses = sc.ops.PoseDetect(frame=sampled)
    out = NamedStream(sc, "poses")
    sc.run(sc.io.Output(poses, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)

    for i, kp in enumerate(out.load()):
        if i < 3:
            print(f"sampled frame {i}: {kp.shape[0]} keypoints, "
                  f"top score {kp[:, 2].max():.3f}")
    print(f"... {out.len()} frames processed")


if __name__ == "__main__":
    main()
