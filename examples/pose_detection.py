"""Pose detection app: per-frame keypoints over a sampled stream, using
the shipped trained weights.  (Reference: examples/apps/pose_detection/
main.py, which loads external OpenPose weights; these weights come from
scanner_tpu.models.pose_train's synthetic localization task.)

Usage: python examples/pose_detection.py [path/to/video.mp4] [stride]
With no video argument a synthetic blob clip is generated and the
reported keypoint-0 positions are checked against the true blob centers.
"""

import os
import sys
import tempfile

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.models  # registers PoseDetect
from scanner_tpu.models.pose_train import WIDTH, synth_blob_video

WEIGHTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "scanner_tpu", "models", "weights",
                       "pose_blobnet_w8.npz")


def main():
    video_path = sys.argv[1] if len(sys.argv) > 1 else None
    stride = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    centers = None
    if video_path is None:
        video_path = os.path.join(tempfile.mkdtemp(prefix="pose_ex_"),
                                  "blob.mp4")
        centers = synth_blob_video(video_path, num_frames=24)
        stride = 1

    sc = Client(db_path=os.path.join(tempfile.mkdtemp(prefix="pose_db_"),
                                     "db"))
    try:
        movie = NamedVideoStream(sc, "pose_movie", path=video_path)

        frames = sc.io.Input([movie])
        sampled = sc.streams.Stride(frames, [{"stride": stride}])
        poses = sc.ops.PoseDetect(frame=sampled, width=WIDTH,
                                  checkpoint_dir=WEIGHTS)
        out = NamedStream(sc, "poses")
        sc.run(sc.io.Output(poses, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite)

        errs = []
        for i, kp in enumerate(out.load()):
            x, y = kp[0, 0] * 4, kp[0, 1] * 4  # heatmap -> frame coords
            line = (f"sampled frame {i}: keypoint0 at ({x:.0f}, {y:.0f}) "
                    f"score {kp[0, 2]:.3f}")
            if centers is not None:
                cx, cy = centers[i * stride]
                errs.append(float(np.hypot(x - cx, y - cy)))
                line += f"  true ({cx:.0f}, {cy:.0f})"
            if i < 5:
                print(line)
        print(f"... {out.len()} frames processed")
        if errs:
            print(f"mean localization error: {np.mean(errs):.2f} px")
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
