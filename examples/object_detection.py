"""Object detection app: per-frame boxes over a video, using the shipped
trained SSD weights.  (Reference: examples/apps/object_detection_tensorflow/
main.py, which downloads an externally-trained SSD-mobilenet; these
weights come from scanner_tpu.models.detect_train's synthetic scene task.)

Usage: python examples/object_detection.py [path/to/video.mp4] [stride]
With no video argument a synthetic rectangle-scene clip is generated and
the reported boxes are scored (recall/IoU) against the ground truth.
"""

import os
import sys
import tempfile

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.models  # registers ObjectDetect
from scanner_tpu.models import unpack_detections
from scanner_tpu.models.detect_train import (WIDTH, box_iou,
                                             synth_scene_video)


def main():
    video_path = sys.argv[1] if len(sys.argv) > 1 else None
    stride = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    truth = None
    if video_path is None:
        video_path = os.path.join(tempfile.mkdtemp(prefix="objdet_ex_"),
                                  "scenes.mp4")
        truth = synth_scene_video(video_path, num_frames=16)

    sc = Client(db_path=os.path.join(tempfile.mkdtemp(prefix="objdet_db_"),
                                     "db"))
    try:
        movie = NamedVideoStream(sc, "objdet_movie", path=video_path)
        frames = sc.io.Input([movie])
        sampled = sc.streams.Stride(frames, [{"stride": stride}])
        # width 8 restores the shipped trained weights by default
        dets = sc.ops.ObjectDetect(frame=sampled, width=WIDTH,
                                   score_thresh=0.3)
        out = NamedStream(sc, "detections")
        sc.run(sc.io.Output(dets, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite)

        hits = total = 0
        for i, det in enumerate(out.load()):
            d = unpack_detections(det)
            boxes, scores = d["boxes"], d["scores"]
            if i < 5:
                tops = ", ".join(
                    f"[{b[0]:.2f} {b[1]:.2f} {b[2]:.2f} {b[3]:.2f}]@"
                    f"{s:.2f}" for b, s in zip(boxes[:3], scores[:3]))
                print(f"frame {i * stride}: {len(boxes)} boxes  {tops}")
            if truth is not None:
                for gt in truth[i * stride]:
                    total += 1
                    if any(box_iou(gt, b) >= 0.3 for b in boxes):
                        hits += 1
        if truth is not None:
            print(f"recall@IoU0.3: {hits}/{total} "
                  f"({100.0 * hits / max(total, 1):.0f}%)")
            assert hits >= 0.7 * total, \
                "shipped detector failed to localize the synthetic scenes"
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
