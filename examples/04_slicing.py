"""Tutorial 04: slicing (reference tutorials/04_slicing.py).

Slice partitions one long stream into independent groups (state resets per
group; groups schedule onto different workers); Unslice stitches results.
"""

import sys

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels


def main():
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    movie = NamedVideoStream(sc, "t04", path=sys.argv[1])
    frames = sc.io.Input([movie])
    sliced = sc.streams.Slice(frames, partitions=[sc.partitioner.all(50)])
    hist = sc.ops.Histogram(frame=sliced)
    unsliced = sc.streams.Unslice(hist)
    out = NamedStream(sc, "t04_hists")
    sc.run(sc.io.Output(unsliced, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)
    print(f"{out.len()} rows across 50-frame slice groups")


if __name__ == "__main__":
    main()
