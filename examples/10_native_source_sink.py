"""Tutorial 10: native (C) sources and sinks.

(Reference: examples/tutorials/09_defining_cpp_sources.py +
10_defining_cpp_sinks.py — the C++ Source/Sink extension API compiled
into a shared library.)

Sources and sinks plug into the engine through `CustomStorage`
(scanner_tpu/storage/custom.py): the loader calls `read_rows`, the saver
calls `write_item`, `finished` is the durability barrier.  When the
container format needs native speed — packed binary records, mmap'd
indexes, hardware-accelerated IO — the storage methods call into a C
library via ctypes, exactly like the built-in video layer
(scanner_tpu/video/lib.py wrapping cpp/scvid.cpp).

This example builds a tiny C "packed record container" at runtime:
one .pack file of concatenated payloads + one .idx file of int64
offsets.  Items land as separate segment files (tasks complete in any
order across workers); `finished` merges them in row order — the same
two-phase commit the built-in column store uses.  The C side does the
packing, merging, and gathered reads; Python stays a thin adapter.

Usage: python examples/10_native_source_sink.py [db_path]
"""

import ctypes
import os
import struct
import subprocess
import sys
import tempfile
from typing import Any, List, Sequence

import numpy as np

from scanner_tpu import (CacheMode, Client, Kernel, PerfParams,
                        register_op)
from scanner_tpu.storage.custom import CustomStorage, CustomStream

C_SRC = r"""
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

// One item segment: [int64 n] [int64 sizes[n]] [payload bytes...]
// written atomically (tmp + rename).
extern "C" __attribute__((visibility("default")))
int pack_write_item(const char* path, const uint8_t* payload,
                    const int64_t* sizes, int64_t n) {
  char tmp[4096];
  snprintf(tmp, sizeof(tmp), "%s.tmp", path);
  FILE* f = fopen(tmp, "wb");
  if (!f) return -1;
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += sizes[i];
  if (fwrite(&n, sizeof(n), 1, f) != 1 ||
      fwrite(sizes, sizeof(int64_t), (size_t)n, f) != (size_t)n ||
      (total > 0 && fwrite(payload, 1, (size_t)total, f) != (size_t)total)) {
    fclose(f);
    remove(tmp);
    return -1;
  }
  if (fflush(f) != 0 || fclose(f) != 0) { remove(tmp); return -1; }
  return rename(tmp, path) == 0 ? 0 : -1;
}

// Merge item segments (given in row order) into pack + idx.
// idx layout: [int64 n_rows] [int64 end_offset[n_rows]]
extern "C" __attribute__((visibility("default")))
int pack_merge(const char* const* item_paths, int64_t n_items,
               const char* pack_path, const char* idx_path) {
  FILE* pf = fopen(pack_path, "wb");
  if (!pf) return -1;
  int64_t n_rows = 0, off = 0;
  int64_t* ends = NULL;
  for (int64_t it = 0; it < n_items; ++it) {
    FILE* f = fopen(item_paths[it], "rb");
    if (!f) { fclose(pf); free(ends); return -1; }
    int64_t n;
    if (fread(&n, sizeof(n), 1, f) != 1) { fclose(f); fclose(pf);
                                           free(ends); return -1; }
    int64_t* sizes = (int64_t*)malloc(sizeof(int64_t) * (size_t)n);
    if (!sizes || fread(sizes, sizeof(int64_t), (size_t)n, f)
                      != (size_t)n) {
      free(sizes); fclose(f); fclose(pf); free(ends); return -1;
    }
    int64_t* grown =
        (int64_t*)realloc(ends, sizeof(int64_t) * (size_t)(n_rows + n));
    if (!grown) {
      free(sizes); fclose(f); fclose(pf); free(ends); return -1;
    }
    ends = grown;
    char buf[1 << 16];
    for (int64_t i = 0; i < n; ++i) {
      int64_t left = sizes[i];
      while (left > 0) {
        size_t chunk = left < (int64_t)sizeof(buf) ? (size_t)left
                                                   : sizeof(buf);
        if (fread(buf, 1, chunk, f) != chunk ||
            fwrite(buf, 1, chunk, pf) != chunk) {
          free(sizes); fclose(f); fclose(pf); free(ends); return -1;
        }
        left -= (int64_t)chunk;
      }
      off += sizes[i];
      ends[n_rows + i] = off;
    }
    n_rows += n;
    free(sizes);
    fclose(f);
  }
  if (fflush(pf) != 0 || fclose(pf) != 0) { free(ends); return -1; }
  char tmp[4096];
  snprintf(tmp, sizeof(tmp), "%s.tmp", idx_path);
  FILE* xf = fopen(tmp, "wb");
  if (!xf) { free(ends); return -1; }
  if (fwrite(&n_rows, sizeof(n_rows), 1, xf) != 1 ||
      (n_rows > 0 && fwrite(ends, sizeof(int64_t), (size_t)n_rows, xf)
                         != (size_t)n_rows)) {
    fclose(xf); remove(tmp); free(ends); return -1;
  }
  free(ends);
  if (fflush(xf) != 0 || fclose(xf) != 0) { remove(tmp); return -1; }
  return rename(tmp, idx_path) == 0 ? 0 : -1;
}

extern "C" __attribute__((visibility("default")))
int64_t pack_num_rows(const char* idx_path) {
  FILE* f = fopen(idx_path, "rb");
  if (!f) return -1;
  int64_t n;
  if (fread(&n, sizeof(n), 1, f) != 1) { fclose(f); return -1; }
  fclose(f);
  return n;
}

// Gathered read: sizes_out[i] = byte length of rows[i]; payload written
// back-to-back into out (caller sized it via a first sizes-only call
// with out == NULL).
extern "C" __attribute__((visibility("default")))
int pack_read_rows(const char* pack_path, const char* idx_path,
                   const int64_t* rows, int64_t n_wanted,
                   int64_t* sizes_out, uint8_t* out) {
  FILE* xf = fopen(idx_path, "rb");
  if (!xf) return -1;
  int64_t n_rows;
  if (fread(&n_rows, sizeof(n_rows), 1, xf) != 1) { fclose(xf); return -1; }
  int64_t* ends = (int64_t*)malloc(sizeof(int64_t) * (size_t)n_rows);
  if (!ends || fread(ends, sizeof(int64_t), (size_t)n_rows, xf)
                   != (size_t)n_rows) {
    free(ends); fclose(xf); return -1;
  }
  fclose(xf);
  FILE* pf = out ? fopen(pack_path, "rb") : NULL;
  if (out && !pf) { free(ends); return -1; }
  int64_t w = 0;
  for (int64_t i = 0; i < n_wanted; ++i) {
    int64_t r = rows[i];
    if (r < 0 || r >= n_rows) { free(ends); if (pf) fclose(pf); return -2; }
    int64_t start = r == 0 ? 0 : ends[r - 1];
    int64_t sz = ends[r] - start;
    sizes_out[i] = sz;
    if (out) {
      if (fseek(pf, (long)start, SEEK_SET) != 0 ||
          fread(out + w, 1, (size_t)sz, pf) != (size_t)sz) {
        free(ends); fclose(pf); return -1;
      }
      w += sz;
    }
  }
  free(ends);
  if (pf) fclose(pf);
  return 0;
}
"""


def build_pack_lib(workdir: str) -> str:
    """Compile the container library; returns the .so path."""
    src = os.path.join(workdir, "pack.cpp")
    so = os.path.join(workdir, "libpack.so")
    with open(src, "w") as f:
        f.write(C_SRC)
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", src, "-o", so],
                   check=True)
    return so


def load_pack_lib(so: str) -> ctypes.CDLL:
    lib = ctypes.CDLL(so)
    lib.pack_write_item.restype = ctypes.c_int
    lib.pack_write_item.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.c_int64]
    lib.pack_merge.restype = ctypes.c_int
    lib.pack_merge.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                               ctypes.c_int64, ctypes.c_char_p,
                               ctypes.c_char_p]
    lib.pack_num_rows.restype = ctypes.c_int64
    lib.pack_num_rows.argtypes = [ctypes.c_char_p]
    lib.pack_read_rows.restype = ctypes.c_int
    lib.pack_read_rows.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p]
    return lib


class PackedStorage(CustomStorage):
    """Packed-record container backed by the C library: rows are byte
    payloads in one .pack file addressed by an .idx offset table.  Items
    written by the sink land as segment files (workers finish tasks in
    any order); `finished` merges them in row order.

    The CDLL handle is loaded LAZILY from the stored .so path — a ctypes
    handle on the instance would make the stream unpicklable, and the
    distributed engine ships job specs (including custom streams) as
    cloudpickle blobs.  The built-in video layer uses the same pattern
    (scanner_tpu/video/lib.py module-level get_lib())."""

    def __init__(self, root: str, so_path: str):
        self.root = root
        self.so_path = so_path
        self._lib = None
        os.makedirs(root, exist_ok=True)

    @property
    def lib(self) -> ctypes.CDLL:
        if self._lib is None:
            self._lib = load_pack_lib(self.so_path)
        return self._lib

    def __getstate__(self):
        d = self.__dict__.copy()
        d["_lib"] = None  # handle is per-process; reload from so_path
        return d

    def _p(self, stream: CustomStream, ext: str) -> str:
        return os.path.join(self.root, f"{stream.name}.{ext}")

    def num_rows(self, stream: CustomStream) -> int:
        n = self.lib.pack_num_rows(self._p(stream, "idx").encode())
        if n < 0:
            raise FileNotFoundError(self._p(stream, "idx"))
        return int(n)

    def read_rows(self, stream: CustomStream,
                  rows: Sequence[int]) -> List[Any]:
        rows_arr = (ctypes.c_int64 * len(rows))(*rows)
        sizes = (ctypes.c_int64 * len(rows))()
        pack = self._p(stream, "pack").encode()
        idx = self._p(stream, "idx").encode()
        # pass 1: sizes only; pass 2: one gathered read
        if self.lib.pack_read_rows(pack, idx, rows_arr, len(rows), sizes,
                                   None) != 0:
            raise IOError(f"pack sizes read failed: {stream.name}")
        total = sum(sizes)
        buf = np.empty(total, np.uint8)
        if self.lib.pack_read_rows(
                pack, idx, rows_arr, len(rows), sizes,
                buf.ctypes.data_as(ctypes.c_void_p)) != 0:
            raise IOError(f"pack payload read failed: {stream.name}")
        out, off = [], 0
        for s in sizes:
            out.append(buf[off:off + s].tobytes())
            off += s
        return out

    def write_item(self, stream: CustomStream, start_row: int,
                   elements: Sequence[Any]) -> None:
        payloads = [bytes(e) for e in elements]
        sizes = (ctypes.c_int64 * len(payloads))(*map(len, payloads))
        blob = b"".join(payloads)
        path = self._p(stream, f"item.{start_row:08d}")
        if self.lib.pack_write_item(path.encode(), blob, sizes,
                                    len(payloads)) != 0:
            raise IOError(f"pack item write failed: {path}")

    def finished(self, stream: CustomStream, total_rows: int) -> None:
        items = sorted(
            f for f in os.listdir(self.root)
            if f.startswith(stream.name + ".item."))
        paths = [os.path.join(self.root, f).encode() for f in items]
        arr = (ctypes.c_char_p * len(paths))(*paths)
        if self.lib.pack_merge(arr, len(paths),
                               self._p(stream, "pack").encode(),
                               self._p(stream, "idx").encode()) != 0:
            raise IOError(f"pack merge failed: {stream.name}")
        # the durability contract passes total_rows exactly so the sink
        # can refuse to commit a short container (a lost segment would
        # otherwise silently shift every later row)
        merged = self.num_rows(stream)
        if merged != total_rows:
            raise IOError(
                f"pack merge produced {merged} rows, job wrote "
                f"{total_rows}: missing segment for {stream.name}")
        for f in items:
            os.remove(os.path.join(self.root, f))

    def exists(self, stream: CustomStream) -> bool:
        return os.path.exists(self._p(stream, "idx"))

    def delete_stream(self, stream: CustomStream) -> None:
        # remove stale item segments too: leftovers from a crashed run
        # would be merged into the NEXT run's container
        stale = [f for f in os.listdir(self.root)
                 if f.startswith(stream.name + ".item.")]
        for f in stale:
            os.remove(os.path.join(self.root, f))
        for ext in ("pack", "idx"):
            try:
                os.remove(self._p(stream, ext))
            except FileNotFoundError:
                pass


@register_op(batch=8)
class PackStats(Kernel):
    """Parse a packed record (int64 seq + float64 value) and return the
    running description string — any Python/JAX op chains off a native
    source exactly like off a video column."""

    def execute(self, rec: Sequence[bytes]) -> Sequence[Any]:
        out = []
        for b in rec:
            seq, val = struct.unpack("<qd", b)
            out.append(struct.pack("<qd", seq * 2, val + 0.5))
        return out


def main() -> None:
    workdir = tempfile.mkdtemp(prefix="sc_tut10_")
    db_path = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(workdir, "db")
    so = build_pack_lib(workdir)
    store = PackedStorage(os.path.join(workdir, "packs"), so)

    # 1. write an input container with the C sink path directly
    n = 40
    src = CustomStream(store, "readings")
    store.write_item(src, 0, [struct.pack("<qd", i, i * 0.25)
                              for i in range(n)])
    store.finished(src, n)
    print(f"packed input: {store.num_rows(src)} rows")

    # 2. run a graph: native source -> op -> native sink
    sc = Client(db_path=db_path)
    try:
        records = sc.io.Input([src])
        doubled = sc.ops.PackStats(rec=records)
        out = CustomStream(store, "derived")
        sc.run(sc.io.Output(doubled, [out]), PerfParams.manual(8, 16),
               cache_mode=CacheMode.Overwrite, show_progress=False)

        # 3. read back through the same native source
        got = list(out.load())
        assert len(got) == n, len(got)
        for i, b in enumerate(got):
            seq, val = struct.unpack("<qd", b)
            assert seq == 2 * i and abs(val - (i * 0.25 + 0.5)) < 1e-9, \
                (i, seq, val)
        print(f"native source -> op -> native sink roundtrip OK "
              f"({n} rows through the packed container)")
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
