"""Tutorial 00: ingest a video, compute per-frame color histograms, read
them back.  (Reference: examples/tutorials/00_basic.py.)

Usage: python examples/00_basic.py path/to/video.mp4 [db_path]
"""

import sys

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels  # registers the stdlib ops (Histogram, ...)


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)

    # declare the input stream; ingests (indexes) the file on first use
    movie = NamedVideoStream(sc, "example_movie", path=video_path)

    # build the computation graph: Input -> Histogram -> Output
    frames = sc.io.Input([movie])
    hists = sc.ops.Histogram(frame=frames)
    out = NamedStream(sc, "example_hists")
    sc.run(sc.io.Output(hists, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)

    for i, h in enumerate(out.load()):
        if i < 3:
            print(f"frame {i}: R-hist {h[0].tolist()}")
    print(f"... {out.len()} histograms total")


if __name__ == "__main__":
    main()
