"""Tutorial 02: op attributes (reference tutorials/02_op_attributes.py).

An op's registration declares how the engine schedules it:

  batch=N           the kernel receives N-row batches — on TPU this is the
                    XLA batch dimension; PerfParams.work_packet_size tunes
                    the actual chunk within the declared cap
  stencil=[...]     each output row sees a window of input rows
                    (REPEAT_EDGE at the boundaries)
  bounded_state=W   stateful with warmup W: the engine replays W rows
                    before each requested range so state is hot
  unbounded_state   stateful with no bounded warmup: rows replay from the
                    start of the stream/slice group
  device=...        DeviceType.TPU kernels get their inputs staged onto
                    the accelerator once per task column

Usage: python examples/02_op_attributes.py path/to/video.mp4 [db_path]
"""

import struct
import sys
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from scanner_tpu import (CacheMode, Client, DeviceType, FrameType, Kernel,
                         NamedStream, NamedVideoStream, PerfParams,
                         register_op)


@register_op(device=DeviceType.TPU, batch=16)
class BatchBrightness(Kernel):
    """batch: one jitted XLA call per chunk instead of per frame."""

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        frames = jnp.asarray(frame, jnp.float32)
        w = jnp.asarray([0.299, 0.587, 0.114])
        return [float(x) for x in (frames * w).sum(-1).mean((1, 2))]


@register_op(device=DeviceType.TPU, stencil=[-1, 0, 1], batch=8)
class TemporalAverage(Kernel):
    """stencil: output row r sees input rows r-1, r, r+1."""

    def execute(self, frame: Sequence[Sequence[FrameType]]
                ) -> Sequence[FrameType]:
        win = jnp.asarray(frame, jnp.float32)  # (batch, 3, H, W, C)
        return jnp.clip(win.mean(axis=1), 0, 255).astype(jnp.uint8)


@register_op(bounded_state=5)
class RunningMax(Kernel):
    """bounded state: a 5-row warmup replays before any requested range,
    so sampling rows [100:110] still sees max over rows >= 95."""

    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.cur = 0.0

    def execute(self, bright: Any) -> bytes:
        self.cur = max(self.cur, float(bright))
        return struct.pack("=d", self.cur)


@register_op(unbounded_state=True)
class FrameCounter(Kernel):
    """unbounded state: the engine replays from row 0 (or the slice
    start), so the count is exact whatever range was requested."""

    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.n = 0

    def execute(self, ignore: FrameType) -> bytes:
        self.n += 1
        return struct.pack("=q", self.n)


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)
    try:
        movie = NamedVideoStream(sc, "attrs_movie", path=video_path)

        frames = sc.io.Input([movie])
        bright = sc.ops.BatchBrightness(frame=frames)
        smoothed = sc.ops.TemporalAverage(frame=frames)
        rmax = sc.ops.RunningMax(bright=bright)
        count = sc.ops.FrameCounter(ignore=frames)

        outs = [NamedStream(sc, n) for n in
                ("attrs_bright", "attrs_smooth", "attrs_max", "attrs_n")]
        sc.run([sc.io.Output(bright, [outs[0]]),
                sc.io.Output(smoothed, [outs[1]]),
                sc.io.Output(rmax, [outs[2]]),
                sc.io.Output(count, [outs[3]])],
               PerfParams.estimate(), cache_mode=CacheMode.Overwrite)

        b = list(outs[0].load())
        m = [struct.unpack("=d", x)[0] for x in outs[2].load()]
        n = [struct.unpack("=q", x)[0] for x in outs[3].load()]
        sm = next(iter(outs[1].load()))
        print(f"{len(b)} frames: brightness[0]={b[0]:.1f}, "
              f"running max[-1]={m[-1]:.1f}, count[-1]={n[-1]}, "
              f"smoothed frame shape={sm.shape}")
        assert n[-1] == len(b)
        assert abs(m[-1] - max(b)) < 1e-6
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
