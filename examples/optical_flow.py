"""Optical flow app: dense per-frame flow fields over a video.
(Reference: examples/apps/optical_flow — OpenCV flow in a kernel; here
the OpticalFlow op is a jitted Horn-Schunck solve on device, a stencil
[-1, 0] op so the engine decodes exactly one extra frame per task.)

Usage: python examples/optical_flow.py path/to/video.mp4 [db_path]
"""

import sys

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels  # registers OpticalFlow


def main():
    video_path = sys.argv[1]
    db_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/scanner_tpu_db"
    sc = Client(db_path=db_path)

    movie = NamedVideoStream(sc, "flow-clip", path=video_path)
    frames = sc.io.Input([movie])
    flow = sc.ops.OpticalFlow(frame=frames)
    out = NamedStream(sc, "flow-fields")
    sc.run(sc.io.Output(flow, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite)

    mags = []
    for i, field in enumerate(out.load()):
        f = np.asarray(field)
        assert f.ndim == 3 and f.shape[2] == 2, f.shape
        mags.append(float(np.linalg.norm(f, axis=2).mean()))
    print(f"{len(mags)} flow fields; mean |flow| per frame: "
          f"min {min(mags):.3f} max {max(mags):.3f}")


if __name__ == "__main__":
    main()
