"""Instance segmentation app: per-frame boxes + masks over a video, using
the shipped trained segmenter weights.  (Reference: examples/apps/detectron,
which runs externally-trained Mask R-CNN via Caffe2 kernels; these weights
come from scanner_tpu.models.seg_train's synthetic shape task.)

Usage: python examples/instance_segmentation.py [path/to/video.mp4] [stride]
With no video argument a synthetic shape-scene clip is generated and the
reported masks are scored (mask IoU against the analytic ground truth).
"""

import os
import sys
import tempfile

import numpy as np

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.models  # registers InstanceSegment
from scanner_tpu.models import paste_masks, unpack_instances
from scanner_tpu.models.detect_train import WIDTH, box_iou
from scanner_tpu.models.seg_train import (SIZE, full_gt_mask,
                                          synth_shape_video)


def main():
    video_path = sys.argv[1] if len(sys.argv) > 1 else None
    stride = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    truth = None
    size = SIZE
    if video_path is None:
        video_path = os.path.join(tempfile.mkdtemp(prefix="seg_ex_"),
                                  "shapes.mp4")
        truth = synth_shape_video(video_path, num_frames=12)

    sc = Client(db_path=os.path.join(tempfile.mkdtemp(prefix="seg_db_"),
                                     "db"))
    try:
        movie = NamedVideoStream(sc, "seg_movie", path=video_path)
        frames = sc.io.Input([movie])
        sampled = sc.streams.Stride(frames, [{"stride": stride}])
        # width 8 restores the shipped trained weights by default
        inst = sc.ops.InstanceSegment(frame=sampled, width=WIDTH,
                                      score_thresh=0.3)
        out = NamedStream(sc, "instances")
        sc.run(sc.io.Output(inst, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite)

        matched = total = 0
        ious = []
        for i, row in enumerate(out.load()):
            r = unpack_instances(row)
            boxes, scores, masks = r["boxes"], r["scores"], r["masks"]
            if i < 4:
                descr = ", ".join(
                    f"[{b[0]:.2f} {b[1]:.2f} {b[2]:.2f} {b[3]:.2f}]@"
                    f"{s:.2f} fill={m.mean():.2f}"
                    for b, s, m in zip(boxes[:3], scores[:3], masks[:3]))
                print(f"frame {i * stride}: {len(boxes)} instances  {descr}")
            if truth is None:
                continue
            gt_boxes, gt_kinds = truth[i * stride]
            full = paste_masks(boxes, masks, size, size)
            for gt_box, gt_kind in zip(gt_boxes, gt_kinds):
                total += 1
                cand = [j for j, b in enumerate(boxes)
                        if box_iou(gt_box, b) >= 0.3]
                if not cand:
                    continue
                matched += 1
                gm = full_gt_mask(gt_box, int(gt_kind), size, size)
                best = max((full[j] & gm).sum() / max((full[j] | gm).sum(), 1)
                           for j in cand)
                ious.append(best)
        if truth is not None:
            mean_iou = float(np.mean(ious)) if ious else 0.0
            print(f"box recall@IoU0.3: {matched}/{total}  "
                  f"mean mask IoU of matches: {mean_iou:.2f}")
            assert matched >= 0.7 * total, \
                "shipped segmenter failed to localize the synthetic shapes"
            assert mean_iou >= 0.5, \
                f"shipped segmenter masks too coarse (IoU {mean_iou:.2f})"
    finally:
        sc.stop()


if __name__ == "__main__":
    main()
