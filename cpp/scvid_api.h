// C ABI of libscvid (see scvid.cpp for semantics).
#pragma once

#include <cstdint>

extern "C" {

struct ScvidIndex {
  int32_t width;
  int32_t height;
  double fps;
  int64_t num_samples;
  char codec[32];
  int32_t tb_num;
  int32_t tb_den;
  uint64_t* sample_offsets;
  uint64_t* sample_sizes;
  int64_t* sample_pts;
  int64_t* sample_dts;
  uint8_t* keyflags;
  uint8_t* extradata;
  int64_t extradata_size;
};

struct ScvidDecoder;
struct ScvidEncoder;

const char* scvid_last_error();
void scvid_set_log_level(int level);
int32_t scvid_api_version();

ScvidIndex* scvid_ingest(const char* in_path, const char* out_packets_path);
void scvid_index_free(ScvidIndex* idx);

ScvidDecoder* scvid_decoder_create(const char* codec_name,
                                   const uint8_t* extradata,
                                   int64_t extradata_size, int32_t width,
                                   int32_t height, int32_t n_threads);
void scvid_decoder_destroy(ScvidDecoder* d);
void scvid_decoder_reset(ScvidDecoder* d);
void scvid_decoder_set_output_format(ScvidDecoder* d, int32_t fmt);
int64_t scvid_decode_run(ScvidDecoder* d, const uint8_t* packets,
                         const uint64_t* pkt_sizes, int64_t n_packets,
                         const uint8_t* wanted, int64_t n_wanted,
                         int32_t flush, uint8_t* out, int64_t out_capacity,
                         int64_t* out_dims);
int64_t scvid_decode_run_pts(ScvidDecoder* d, const uint8_t* packets,
                             const uint64_t* pkt_sizes,
                             const int64_t* pkt_pts, int64_t n_packets,
                             const int64_t* wanted_pts, int64_t n_wanted,
                             uint8_t* deliv, int32_t flush, uint8_t* out,
                             int64_t out_capacity, int64_t* out_dims);
int64_t scvid_decoder_emitted(ScvidDecoder* d);
int64_t scvid_decode_run_pts_stream(
    ScvidDecoder* d, const uint8_t* packets, const uint64_t* pkt_sizes,
    const int64_t* pkt_pts, int64_t n_packets, const int64_t* wanted_pts,
    int64_t n_wanted, uint8_t* deliv, int32_t flush, int64_t max_frames,
    uint8_t* out, int64_t out_capacity, int64_t* out_dims,
    int64_t* consumed);

ScvidEncoder* scvid_encoder_create(int32_t width, int32_t height,
                                   int32_t fps_num, int32_t fps_den,
                                   const char* codec_name, int64_t bitrate,
                                   int32_t crf, int32_t keyint,
                                   int32_t bframes, int32_t open_gop);
void scvid_encoder_destroy(ScvidEncoder* e);
int64_t scvid_encoder_extradata(ScvidEncoder* e, uint8_t* buf,
                                int64_t bufsize);
const char* scvid_encoder_descriptor(ScvidEncoder* e);
int32_t scvid_encoder_feed(ScvidEncoder* e, const uint8_t* rgb,
                           int64_t n_frames);
int32_t scvid_encoder_feed_pts(ScvidEncoder* e, const uint8_t* rgb,
                               int64_t n_frames, const int64_t* pts);
int32_t scvid_encoder_flush(ScvidEncoder* e);
int64_t scvid_encoder_pending(ScvidEncoder* e);
int64_t scvid_encoder_pending_bytes(ScvidEncoder* e);
void scvid_encoder_take(ScvidEncoder* e, uint8_t* data, uint64_t* sizes,
                        uint8_t* keys, int64_t* pts, int64_t* dts);

int32_t scvid_mp4_write(const char* path, int32_t width, int32_t height,
                        int32_t fps_num, int32_t fps_den, int32_t tb_num,
                        int32_t tb_den, const char* codec_name,
                        const uint8_t* extradata, int64_t extradata_size,
                        const uint8_t* packets, const uint64_t* pkt_sizes,
                        const uint8_t* keys, const int64_t* pts,
                        const int64_t* dts, int64_t n_packets);

}  // extern "C"
