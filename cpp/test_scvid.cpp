// Native test harness for libscvid (reference analogue:
// tests/ffmpeg_test.cpp + scanner/video/decoder_automata_test.cpp gtest).
//
// Exercises encode -> mux -> ingest/index -> selective decode without
// Python, so it can run under ASan/UBSan/TSan (`make asan && ./test_scvid`).
// Exits nonzero on any failure; prints one line per check.

#include <cassert>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>
#include <algorithm>
#include <thread>

#include "scvid_api.h"

#define CHECK(cond, msg)                                       \
  do {                                                         \
    if (!(cond)) {                                             \
      fprintf(stderr, "FAIL: %s (%s:%d)\n", msg, __FILE__,     \
              __LINE__);                                       \
      exit(1);                                                 \
    }                                                          \
    printf("ok: %s\n", msg);                                   \
  } while (0)

static const int W = 64, H = 48, N = 40, KEYINT = 8;

static void fill_frame(uint8_t* rgb, int i) {
  // R encodes the frame id; G is a SMOOTH horizontal ramp (a per-pixel
  // sawtooth would put high-frequency energy into chroma, and 4:2:0
  // subsampling then bleeds it into decoded R, breaking frame_id)
  for (int y = 0; y < H; ++y) {
    for (int x = 0; x < W; ++x) {
      int p = y * W + x;
      rgb[3 * p + 0] = (uint8_t)((i * 16) % 224);
      rgb[3 * p + 1] = (uint8_t)((x * 239) / (W - 1));
      rgb[3 * p + 2] = 0;
    }
  }
  // moving bright square: per-frame motion like the Python fixture's
  // (B-frame emission itself is guaranteed by b-adapt=0 in the encoder
  // when bframes>0 — the motion just keeps the clip non-degenerate)
  int sq = 8, sx = (i * 5) % (W - sq);
  for (int y = 0; y < sq; ++y)
    for (int x = sx; x < sx + sq; ++x) rgb[3 * (y * W + x) + 2] = 230;
}

static int frame_id(const uint8_t* rgb) {
  long sum = 0;
  for (int p = 0; p < W * H; ++p) sum += rgb[3 * p];
  return (int)((sum / (W * H) + 8) / 16) % 14;
}

int main() {
  const char* mp4 = "/tmp/scvid_test.mp4";
  const char* pkts = "/tmp/scvid_test.pkts";

  // --- encode a deterministic clip -------------------------------------
  ScvidEncoder* enc = scvid_encoder_create(W, H, 24, 1, "libx264", 0, 18,
                                           KEYINT, 0, 0);
  CHECK(enc != nullptr, "encoder create");
  std::vector<uint8_t> frame(W * H * 3);
  for (int i = 0; i < N; ++i) {
    fill_frame(frame.data(), i);
    CHECK(scvid_encoder_feed(enc, frame.data(), 1) == 0, "encoder feed");
  }
  CHECK(scvid_encoder_flush(enc) == 0, "encoder flush");
  int64_t np = scvid_encoder_pending(enc);
  CHECK(np == N, "one packet per frame");
  int64_t nbytes = scvid_encoder_pending_bytes(enc);
  std::vector<uint8_t> data(nbytes);
  std::vector<uint64_t> sizes(np);
  std::vector<uint8_t> keys(np);
  std::vector<int64_t> pts(np), dts(np);
  scvid_encoder_take(enc, data.data(), sizes.data(), keys.data(),
                     pts.data(), dts.data());
  CHECK(keys[0] == 1, "first packet is a keyframe");

  int64_t xsz = scvid_encoder_extradata(enc, nullptr, 0);
  CHECK(xsz > 0, "encoder extradata present");
  std::vector<uint8_t> extradata(xsz);
  scvid_encoder_extradata(enc, extradata.data(), xsz);

  // --- mux to mp4 -------------------------------------------------------
  CHECK(scvid_mp4_write(mp4, W, H, 24, 1, 1, 24, "h264", extradata.data(),
                        xsz, data.data(), sizes.data(), keys.data(),
                        pts.data(), dts.data(), np) == 0,
        "mp4 write");
  scvid_encoder_destroy(enc);

  // --- ingest/index -----------------------------------------------------
  ScvidIndex* idx = scvid_ingest(mp4, pkts);
  CHECK(idx != nullptr, "ingest");
  CHECK(idx->num_samples == N, "sample count");
  CHECK(idx->width == W && idx->height == H, "geometry");
  int nkeys = 0;
  for (int i = 0; i < N; ++i) nkeys += idx->keyflags[i];
  CHECK(nkeys >= N / KEYINT, "keyframe count");

  // --- selective decode: one mid-GOP frame ------------------------------
  // find the keyframe governing display frame 13
  int kf = 0;
  for (int i = 0; i <= 13; ++i)
    if (idx->keyflags[i]) kf = i;
  ScvidDecoder* dec = scvid_decoder_create("h264", idx->extradata,
                                           idx->extradata_size, W, H, 1);
  CHECK(dec != nullptr, "decoder create");
  FILE* f = fopen(pkts, "rb");
  CHECK(f != nullptr, "packet file open");
  long off = (long)idx->sample_offsets[kf];
  long end = (long)(idx->sample_offsets[13] + idx->sample_sizes[13]);
  std::vector<uint8_t> run(end - off);
  fseek(f, off, SEEK_SET);
  CHECK(fread(run.data(), 1, run.size(), f) == run.size(), "packet read");
  fclose(f);
  std::vector<uint64_t> run_sizes;
  for (int i = kf; i <= 13; ++i) run_sizes.push_back(idx->sample_sizes[i]);
  std::vector<uint8_t> wanted(13 - kf + 1, 0);
  wanted.back() = 1;
  std::vector<uint8_t> out(W * H * 3);
  int64_t dims[2] = {0, 0};
  int64_t got = scvid_decode_run(dec, run.data(), run_sizes.data(),
                                 (int64_t)run_sizes.size(), wanted.data(),
                                 (int64_t)wanted.size(), 1, out.data(),
                                 (int64_t)out.size(), dims);
  CHECK(got == 1, "exactly one frame decoded");
  CHECK(dims[0] == H && dims[1] == W, "decoded geometry");
  CHECK(frame_id(out.data()) == (13 * 16 % 224 + 8) / 16 % 14,
        "decoded frame identity");

  // --- capacity guard ---------------------------------------------------
  scvid_decoder_reset(dec);
  int64_t bad = scvid_decode_run(dec, run.data(), run_sizes.data(),
                                 (int64_t)run_sizes.size(), wanted.data(),
                                 (int64_t)wanted.size(), 1, out.data(),
                                 16 /* too small */, dims);
  CHECK(bad == -1, "undersized buffer rejected");

  scvid_decoder_destroy(dec);
  scvid_index_free(idx);
  remove(mp4);
  remove(pkts);

  // --- B-frame stream: encode -> mux -> ingest -> full decode -----------
  // bframes>0 produces a reordered (pts != dts) stream; the decoder must
  // still emit display-ordered frames with correct content.
  {
    const char* bmp4 = "/tmp/scvid_test_b.mp4";
    const char* bpkts = "/tmp/scvid_test_b.pkts";
    ScvidEncoder* benc = scvid_encoder_create(W, H, 24, 1, "libx264", 0,
                                              18, KEYINT, 2, 0);
    CHECK(benc != nullptr, "bframe encoder create");
    for (int i = 0; i < N; ++i) {
      fill_frame(frame.data(), i);
      CHECK(scvid_encoder_feed(benc, frame.data(), 1) == 0,
            "bframe encoder feed");
    }
    CHECK(scvid_encoder_flush(benc) == 0, "bframe encoder flush");
    int64_t bn = scvid_encoder_pending(benc);
    CHECK(bn == N, "bframe one packet per frame");
    std::vector<uint8_t> bdata(scvid_encoder_pending_bytes(benc));
    std::vector<uint64_t> bsizes(bn);
    std::vector<uint8_t> bkeys(bn);
    std::vector<int64_t> bpts(bn), bdts(bn);
    scvid_encoder_take(benc, bdata.data(), bsizes.data(), bkeys.data(),
                       bpts.data(), bdts.data());
    bool reordered = false;
    for (int i = 1; i < N; ++i)
      if (bpts[i] < bpts[i - 1]) reordered = true;
    CHECK(reordered, "bframe stream actually reorders (pts != dts)");
    int64_t bx = scvid_encoder_extradata(benc, nullptr, 0);
    std::vector<uint8_t> bextra(bx);
    scvid_encoder_extradata(benc, bextra.data(), bx);
    CHECK(scvid_mp4_write(bmp4, W, H, 24, 1, 1, 24, "h264", bextra.data(),
                          bx, bdata.data(), bsizes.data(), bkeys.data(),
                          bpts.data(), bdts.data(), bn) == 0,
          "bframe mp4 write");
    scvid_encoder_destroy(benc);

    ScvidIndex* bidx = scvid_ingest(bmp4, bpkts);
    CHECK(bidx != nullptr, "bframe ingest");
    CHECK(bidx->num_samples == N, "bframe sample count");
    ScvidDecoder* bdec = scvid_decoder_create("h264", bidx->extradata,
                                              bidx->extradata_size, W, H,
                                              1);
    CHECK(bdec != nullptr, "bframe decoder create");
    FILE* bf = fopen(bpkts, "rb");
    CHECK(bf != nullptr, "bframe packet file open");
    long total = (long)(bidx->sample_offsets[N - 1] +
                        bidx->sample_sizes[N - 1]);
    std::vector<uint8_t> ball(total);
    CHECK(fread(ball.data(), 1, ball.size(), bf) == ball.size(),
          "bframe packet read");
    fclose(bf);
    std::vector<uint64_t> ball_sizes(bidx->sample_sizes,
                                     bidx->sample_sizes + N);
    std::vector<uint8_t> ball_wanted(N, 1);
    std::vector<uint8_t> bout((size_t)N * W * H * 3);
    int64_t bdims[2] = {0, 0};
    int64_t bgot = scvid_decode_run(bdec, ball.data(), ball_sizes.data(),
                                    N, ball_wanted.data(), N, 1,
                                    bout.data(), (int64_t)bout.size(),
                                    bdims);
    CHECK(bgot == N, "bframe full decode emits every frame");
    bool ids_ok = true;
    for (int i = 0; i < N; ++i)
      if (frame_id(bout.data() + (size_t)i * W * H * 3) !=
          (i * 16 % 224 + 8) / 16 % 14)
        ids_ok = false;
    CHECK(ids_ok, "bframe frames emitted in display order with correct "
                  "content");

    // --- pts-matched selection on the same reordered stream -------------
    // Request a sparse display-order subset by timestamp; delivery must
    // be exact and the deliv mask complete (the open-GOP/VFR decode path).
    {
      // display order = pts ascending; pick every 7th display frame.
      // NOTE: wanted/packet pts must share a clock — use the ingested
      // index's container-timescale pts for both (encoder-tick pts from
      // take_packets are a different clock after muxing)
      std::vector<int64_t> sorted_pts(bidx->sample_pts,
                                      bidx->sample_pts + N);
      std::sort(sorted_pts.begin(), sorted_pts.end());
      std::vector<int64_t> wanted_pts;
      for (int i = 0; i < N; i += 7) wanted_pts.push_back(sorted_pts[i]);
      std::vector<int64_t> pkt_pts(bidx->sample_pts,
                                   bidx->sample_pts + N);
      std::vector<uint8_t> deliv(wanted_pts.size());
      std::vector<uint8_t> pout(wanted_pts.size() * (size_t)W * H * 3);
      int64_t pdims[2] = {0, 0};
      scvid_decoder_reset(bdec);
      int64_t pgot = scvid_decode_run_pts(
          bdec, ball.data(), ball_sizes.data(), pkt_pts.data(), N,
          wanted_pts.data(), (int64_t)wanted_pts.size(), deliv.data(), 1,
          pout.data(), (int64_t)pout.size(), pdims);
      CHECK(pgot == (int64_t)wanted_pts.size(),
            "pts-matched decode delivers every wanted frame");
      bool deliv_ok = true;
      for (auto d : deliv)
        if (!d) deliv_ok = false;
      CHECK(deliv_ok, "pts-matched deliv mask complete");
      bool pids_ok = true;
      for (size_t i = 0; i < wanted_pts.size(); ++i) {
        int disp = (int)(i * 7);
        if (frame_id(pout.data() + i * (size_t)W * H * 3) !=
            (disp * 16 % 224 + 8) / 16 % 14)
          pids_ok = false;
      }
      CHECK(pids_ok, "pts-matched frames carry the right content");
    }
    // --- concurrent decoders (the engine's loader-thread model) ---------
    // N loader threads each own a decoder and decode overlapping frame
    // sets of the SAME stream concurrently — the GIL-free concurrency the
    // Python engine relies on.  Run under `make tsan` to prove the
    // library has no data races across handles (thread_local error
    // state, no shared mutable globals).
    {
      const int NT = 4;
      std::vector<std::thread> threads;
      std::vector<int> oks(NT, 0);
      for (int t = 0; t < NT; ++t) {
        threads.emplace_back([&, t]() {
          ScvidDecoder* d = scvid_decoder_create(
              "h264", bidx->extradata, bidx->extradata_size, W, H, 1);
          if (!d) return;
          std::vector<uint8_t> out((size_t)N * W * H * 3);
          std::vector<uint8_t> want(N, 1);
          int64_t dims[2] = {0, 0};
          for (int rep = 0; rep < 3; ++rep) {
            scvid_decoder_reset(d);
            int64_t got = scvid_decode_run(
                d, ball.data(), ball_sizes.data(), N, want.data(), N, 1,
                out.data(), (int64_t)out.size(), dims);
            if (got != N) { scvid_decoder_destroy(d); return; }
            int id0 = frame_id(out.data());
            int idt = frame_id(out.data() +
                               (size_t)(N - 1) * W * H * 3);
            if (id0 != (0 * 16 % 224 + 8) / 16 % 14 ||
                idt != ((N - 1) * 16 % 224 + 8) / 16 % 14) {
              scvid_decoder_destroy(d);
              return;
            }
          }
          scvid_decoder_destroy(d);
          oks[t] = 1;
        });
      }
      for (auto& th : threads) th.join();
      int total = 0;
      for (int ok : oks) total += ok;
      CHECK(total == NT, "4 concurrent decoders on one stream all exact");
    }

    scvid_decoder_destroy(bdec);
    scvid_index_free(bidx);
    remove(bmp4);
    remove(bpkts);
  }

  // --- unaligned width (regression: heap corruption at w % 16 != 0) -----
  // Tight-packed swscale output overran SIMD row writes for widths not a
  // multiple of 16; convert_frame now routes those through an aligned
  // scratch surface.  Decode a 90x70 clip into an EXACTLY-sized buffer
  // with canary bytes behind it — run under `make asan` for the full
  // proof; the canary catches gross overruns even without it.
  {
    const int UW = 90, UH = 70, UN = 24;
    const char* ump4 = "/tmp/scvid_test_u.mp4";
    const char* upkts = "/tmp/scvid_test_u.pkts";
    ScvidEncoder* uenc = scvid_encoder_create(UW, UH, 24, 1, "libx264", 0,
                                              18, KEYINT, 0, 0);
    CHECK(uenc != nullptr, "unaligned encoder create");
    // feed every frame from ONE exactly-sized tight-packed buffer: the
    // encoder's swscale SOURCE rows have the same SIMD overrun hazard
    // on the read side (feed_pts now stages unaligned widths through
    // an over-aligned scratch); under `make asan` an exactly-sized
    // heap allocation proves no row read escapes any frame
    std::vector<uint8_t> uframes((size_t)UN * UW * UH * 3);
    for (int i = 0; i < UN; ++i) {
      uint8_t* uframe = uframes.data() + (size_t)i * UW * UH * 3;
      for (int p = 0; p < UW * UH; ++p) {
        uframe[3 * p + 0] = (uint8_t)((i * 16) % 224);
        uframe[3 * p + 1] = (uint8_t)(((p % UW) * 239) / (UW - 1));
        uframe[3 * p + 2] = 0;
      }
    }
    CHECK(scvid_encoder_feed(uenc, uframes.data(), UN) == 0,
          "unaligned encoder batched feed");
    CHECK(scvid_encoder_flush(uenc) == 0, "unaligned encoder flush");
    int64_t un = scvid_encoder_pending(uenc);
    std::vector<uint8_t> udata(scvid_encoder_pending_bytes(uenc));
    std::vector<uint64_t> usizes(un);
    std::vector<uint8_t> ukeys(un);
    std::vector<int64_t> upts(un), udts(un);
    scvid_encoder_take(uenc, udata.data(), usizes.data(), ukeys.data(),
                       upts.data(), udts.data());
    int64_t uxsz = scvid_encoder_extradata(uenc, nullptr, 0);
    std::vector<uint8_t> uextra(uxsz);
    scvid_encoder_extradata(uenc, uextra.data(), uxsz);
    CHECK(scvid_mp4_write(ump4, UW, UH, 24, 1, 1, 24, "h264",
                          uextra.data(), uxsz, udata.data(),
                          usizes.data(), ukeys.data(), upts.data(),
                          udts.data(), un) == 0,
          "unaligned mp4 write");
    scvid_encoder_destroy(uenc);

    ScvidIndex* uidx = scvid_ingest(ump4, upkts);
    CHECK(uidx != nullptr, "unaligned ingest");
    CHECK(uidx->width == UW && uidx->height == UH, "unaligned geometry");
    FILE* uf = fopen(upkts, "rb");
    CHECK(uf != nullptr, "unaligned packet file open");
    long utotal = (long)(uidx->sample_offsets[un - 1] +
                         uidx->sample_sizes[un - 1]);
    std::vector<uint8_t> uall(utotal);
    CHECK(fread(uall.data(), 1, uall.size(), uf) == uall.size(),
          "unaligned packet read");
    fclose(uf);
    std::vector<uint64_t> uall_sizes(uidx->sample_sizes,
                                     uidx->sample_sizes + un);
    std::vector<uint8_t> uwant(un, 1);
    const size_t ubytes = (size_t)un * UW * UH * 3;
    const size_t canary = 256;
    std::vector<uint8_t> uout(ubytes + canary);
    memset(uout.data() + ubytes, 0xAB, canary);
    // rgb24 path
    ScvidDecoder* udec = scvid_decoder_create("h264", uidx->extradata,
                                              uidx->extradata_size, UW,
                                              UH, 1);
    CHECK(udec != nullptr, "unaligned decoder create");
    int64_t udims[2] = {0, 0};
    int64_t ugot = scvid_decode_run(udec, uall.data(), uall_sizes.data(),
                                    un, uwant.data(), un, 1, uout.data(),
                                    (int64_t)ubytes, udims);
    CHECK(ugot == un, "unaligned rgb24 decode emits every frame");
    CHECK(udims[0] == UH && udims[1] == UW, "unaligned decoded geometry");
    bool ucanary_ok = true;
    for (size_t i = 0; i < canary; ++i)
      if (uout[ubytes + i] != 0xAB) ucanary_ok = false;
    CHECK(ucanary_ok, "unaligned rgb24 decode stays inside its buffer");
    bool uids_ok = true;
    for (int i = 0; i < UN; ++i) {
      long sum = 0;
      const uint8_t* fr = uout.data() + (size_t)i * UW * UH * 3;
      for (int p = 0; p < UW * UH; ++p) sum += fr[3 * p];
      if ((int)((sum / (UW * UH) + 8) / 16) % 14 !=
          (i * 16 % 224 + 8) / 16 % 14)
        uids_ok = false;
    }
    CHECK(uids_ok, "unaligned rgb24 frames carry the right content");
    // yuv420 wire path exercises the planar copy/scratch flavor
    scvid_decoder_reset(udec);
    scvid_decoder_set_output_format(udec, 1);
    const int64_t ch = (UH + 1) / 2, cw = (UW + 1) / 2;
    const size_t ybytes = (size_t)un * (UW * UH + 2 * ch * cw);
    std::vector<uint8_t> yout(ybytes + canary);
    memset(yout.data() + ybytes, 0xCD, canary);
    int64_t ygot = scvid_decode_run(udec, uall.data(), uall_sizes.data(),
                                    un, uwant.data(), un, 1, yout.data(),
                                    (int64_t)ybytes, udims);
    CHECK(ygot == un, "unaligned yuv420 decode emits every frame");
    bool ycanary_ok = true;
    for (size_t i = 0; i < canary; ++i)
      if (yout[ybytes + i] != 0xCD) ycanary_ok = false;
    CHECK(ycanary_ok, "unaligned yuv420 decode stays inside its buffer");
    scvid_decoder_destroy(udec);
    scvid_index_free(uidx);
    remove(ump4);
    remove(upkts);
  }

  printf("all native checks passed\n");
  return 0;
}
