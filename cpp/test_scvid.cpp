// Native test harness for libscvid (reference analogue:
// tests/ffmpeg_test.cpp + scanner/video/decoder_automata_test.cpp gtest).
//
// Exercises encode -> mux -> ingest/index -> selective decode without
// Python, so it can run under ASan/UBSan/TSan (`make asan && ./test_scvid`).
// Exits nonzero on any failure; prints one line per check.

#include <cassert>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "scvid_api.h"

#define CHECK(cond, msg)                                       \
  do {                                                         \
    if (!(cond)) {                                             \
      fprintf(stderr, "FAIL: %s (%s:%d)\n", msg, __FILE__,     \
              __LINE__);                                       \
      exit(1);                                                 \
    }                                                          \
    printf("ok: %s\n", msg);                                   \
  } while (0)

static const int W = 64, H = 48, N = 40, KEYINT = 8;

static void fill_frame(uint8_t* rgb, int i) {
  for (int p = 0; p < W * H; ++p) {
    rgb[3 * p + 0] = (uint8_t)((i * 16) % 224);
    rgb[3 * p + 1] = (uint8_t)(p % 240);
    rgb[3 * p + 2] = 0;
  }
}

static int frame_id(const uint8_t* rgb) {
  long sum = 0;
  for (int p = 0; p < W * H; ++p) sum += rgb[3 * p];
  return (int)((sum / (W * H) + 8) / 16) % 14;
}

int main() {
  const char* mp4 = "/tmp/scvid_test.mp4";
  const char* pkts = "/tmp/scvid_test.pkts";

  // --- encode a deterministic clip -------------------------------------
  ScvidEncoder* enc = scvid_encoder_create(W, H, 24, 1, "libx264", 0, 18,
                                           KEYINT, 0);
  CHECK(enc != nullptr, "encoder create");
  std::vector<uint8_t> frame(W * H * 3);
  for (int i = 0; i < N; ++i) {
    fill_frame(frame.data(), i);
    CHECK(scvid_encoder_feed(enc, frame.data(), 1) == 0, "encoder feed");
  }
  CHECK(scvid_encoder_flush(enc) == 0, "encoder flush");
  int64_t np = scvid_encoder_pending(enc);
  CHECK(np == N, "one packet per frame");
  int64_t nbytes = scvid_encoder_pending_bytes(enc);
  std::vector<uint8_t> data(nbytes);
  std::vector<uint64_t> sizes(np);
  std::vector<uint8_t> keys(np);
  std::vector<int64_t> pts(np), dts(np);
  scvid_encoder_take(enc, data.data(), sizes.data(), keys.data(),
                     pts.data(), dts.data());
  CHECK(keys[0] == 1, "first packet is a keyframe");

  int64_t xsz = scvid_encoder_extradata(enc, nullptr, 0);
  CHECK(xsz > 0, "encoder extradata present");
  std::vector<uint8_t> extradata(xsz);
  scvid_encoder_extradata(enc, extradata.data(), xsz);

  // --- mux to mp4 -------------------------------------------------------
  CHECK(scvid_mp4_write(mp4, W, H, 24, 1, 1, 24, "h264", extradata.data(),
                        xsz, data.data(), sizes.data(), keys.data(),
                        pts.data(), dts.data(), np) == 0,
        "mp4 write");
  scvid_encoder_destroy(enc);

  // --- ingest/index -----------------------------------------------------
  ScvidIndex* idx = scvid_ingest(mp4, pkts);
  CHECK(idx != nullptr, "ingest");
  CHECK(idx->num_samples == N, "sample count");
  CHECK(idx->width == W && idx->height == H, "geometry");
  int nkeys = 0;
  for (int i = 0; i < N; ++i) nkeys += idx->keyflags[i];
  CHECK(nkeys >= N / KEYINT, "keyframe count");

  // --- selective decode: one mid-GOP frame ------------------------------
  // find the keyframe governing display frame 13
  int kf = 0;
  for (int i = 0; i <= 13; ++i)
    if (idx->keyflags[i]) kf = i;
  ScvidDecoder* dec = scvid_decoder_create("h264", idx->extradata,
                                           idx->extradata_size, W, H, 1);
  CHECK(dec != nullptr, "decoder create");
  FILE* f = fopen(pkts, "rb");
  CHECK(f != nullptr, "packet file open");
  long off = (long)idx->sample_offsets[kf];
  long end = (long)(idx->sample_offsets[13] + idx->sample_sizes[13]);
  std::vector<uint8_t> run(end - off);
  fseek(f, off, SEEK_SET);
  CHECK(fread(run.data(), 1, run.size(), f) == run.size(), "packet read");
  fclose(f);
  std::vector<uint64_t> run_sizes;
  for (int i = kf; i <= 13; ++i) run_sizes.push_back(idx->sample_sizes[i]);
  std::vector<uint8_t> wanted(13 - kf + 1, 0);
  wanted.back() = 1;
  std::vector<uint8_t> out(W * H * 3);
  int64_t dims[2] = {0, 0};
  int64_t got = scvid_decode_run(dec, run.data(), run_sizes.data(),
                                 (int64_t)run_sizes.size(), wanted.data(),
                                 (int64_t)wanted.size(), 1, out.data(),
                                 (int64_t)out.size(), dims);
  CHECK(got == 1, "exactly one frame decoded");
  CHECK(dims[0] == H && dims[1] == W, "decoded geometry");
  CHECK(frame_id(out.data()) == (13 * 16 % 224 + 8) / 16 % 14,
        "decoded frame identity");

  // --- capacity guard ---------------------------------------------------
  scvid_decoder_reset(dec);
  int64_t bad = scvid_decode_run(dec, run.data(), run_sizes.data(),
                                 (int64_t)run_sizes.size(), wanted.data(),
                                 (int64_t)wanted.size(), 1, out.data(),
                                 16 /* too small */, dims);
  CHECK(bad == -1, "undersized buffer rejected");

  scvid_decoder_destroy(dec);
  scvid_index_free(idx);
  remove(mp4);
  remove(pkts);
  printf("all native checks passed\n");
  return 0;
}
