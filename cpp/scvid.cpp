// libscvid: native video layer for scanner_tpu.
//
// Capability parity with the reference's scanner/video/ stack:
//   - ingest/index      (reference ingest.cpp:867, h264_byte_stream_index_creator.cpp)
//   - exact-frame decode (reference decoder_automata.cpp, software_video_decoder.cpp)
//   - re-encode          (reference software_video_encoder.cpp)
//   - mp4 export         (reference storage.py save_mp4)
//
// Design differences (TPU-native, not a port):
//   * Codec-agnostic container index: per-sample offsets/sizes/keyframe flags
//     come from the demuxer, not a hand-rolled H.264 NAL parser, so any
//     libavcodec codec ingests; H.264/libx264 is the encode path.
//   * C ABI for ctypes.  Python threads call in parallel (ctypes drops the
//     GIL), so N decoder handles = N truly parallel decode pipelines feeding
//     one TPU.
//   * Batch decode-range call: one crossing decodes a keyframe-aligned packet
//     run into a caller-owned RGB24 buffer, selecting only wanted frames —
//     the DecoderAutomata contract in a single call.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/imgutils.h>
#include <libavutil/opt.h>
#include <libswscale/swscale.h>
}

#include "scvid_api.h"

#define SCVID_API extern "C" __attribute__((visibility("default")))

namespace {

thread_local std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

void set_av_error(const std::string& prefix, int err) {
  char buf[256];
  av_strerror(err, buf, sizeof(buf));
  g_error = prefix + ": " + buf;
}

}  // namespace

SCVID_API const char* scvid_last_error() { return g_error.c_str(); }

SCVID_API void scvid_set_log_level(int level) { av_log_set_level(level); }

// Bumped whenever the exported symbol set or struct layouts change; the
// Python loader (video/lib.py) refuses a mismatched prebuilt .so with a
// clear "rebuild" error instead of a late AttributeError.
SCVID_API int32_t scvid_api_version() { return 3; }

// ---------------------------------------------------------------------------
// Ingest: demux a container, write the packet stream, return the index.
// ---------------------------------------------------------------------------

// ScvidIndex layout lives in scvid_api.h; `new ScvidIndex()` below
// value-initializes every field to zero.

SCVID_API void scvid_index_free(ScvidIndex* idx) {
  if (!idx) return;
  delete[] idx->sample_offsets;
  delete[] idx->sample_sizes;
  delete[] idx->sample_pts;
  delete[] idx->sample_dts;
  delete[] idx->keyflags;
  delete[] idx->extradata;
  delete idx;
}

// Demux `in_path`. If out_packets_path != NULL, concatenated packet payloads
// are written there and offsets index that file (normal ingest).  If NULL,
// offsets are the packets' byte positions inside the original container
// (in-place ingest, reference ingest.cpp:382 parse_video_inplace); fails if
// the container does not expose packet positions.
SCVID_API ScvidIndex* scvid_ingest(const char* in_path,
                                   const char* out_packets_path) {
  AVFormatContext* fmt = nullptr;
  int err = avformat_open_input(&fmt, in_path, nullptr, nullptr);
  if (err < 0) {
    set_av_error(std::string("open ") + in_path, err);
    return nullptr;
  }
  err = avformat_find_stream_info(fmt, nullptr);
  if (err < 0) {
    set_av_error("find_stream_info", err);
    avformat_close_input(&fmt);
    return nullptr;
  }
  int stream_idx =
      av_find_best_stream(fmt, AVMEDIA_TYPE_VIDEO, -1, -1, nullptr, 0);
  if (stream_idx < 0) {
    set_error("no video stream found");
    avformat_close_input(&fmt);
    return nullptr;
  }
  AVStream* stream = fmt->streams[stream_idx];
  const AVCodecParameters* par = stream->codecpar;
  const AVCodecDescriptor* desc = avcodec_descriptor_get(par->codec_id);

  FILE* out = nullptr;
  if (out_packets_path) {
    out = fopen(out_packets_path, "wb");
    if (!out) {
      set_error(std::string("cannot open for write: ") + out_packets_path);
      avformat_close_input(&fmt);
      return nullptr;
    }
  }

  std::vector<uint64_t> offsets, sizes;
  std::vector<int64_t> pts, dts;
  std::vector<uint8_t> keys;
  uint64_t write_off = 0;
  bool inplace_ok = true;

  AVPacket* pkt = av_packet_alloc();
  while (av_read_frame(fmt, pkt) >= 0) {
    if (pkt->stream_index == stream_idx) {
      if (out) {
        offsets.push_back(write_off);
        fwrite(pkt->data, 1, pkt->size, out);
        write_off += pkt->size;
      } else {
        if (pkt->pos < 0) inplace_ok = false;
        offsets.push_back(pkt->pos < 0 ? 0 : (uint64_t)pkt->pos);
      }
      sizes.push_back((uint64_t)pkt->size);
      pts.push_back(pkt->pts == AV_NOPTS_VALUE ? (int64_t)pts.size()
                                               : pkt->pts);
      dts.push_back(pkt->dts == AV_NOPTS_VALUE ? (int64_t)dts.size() - 1
                                               : pkt->dts);
      keys.push_back((pkt->flags & AV_PKT_FLAG_KEY) ? 1 : 0);
    }
    av_packet_unref(pkt);
  }
  av_packet_free(&pkt);
  if (out) fclose(out);

  if (!out_packets_path && !inplace_ok) {
    set_error("container does not expose packet positions; in-place ingest "
              "unsupported for this file");
    avformat_close_input(&fmt);
    return nullptr;
  }
  if (offsets.empty()) {
    set_error("no packets in video stream");
    avformat_close_input(&fmt);
    return nullptr;
  }

  ScvidIndex* idx = new ScvidIndex();
  idx->width = par->width;
  idx->height = par->height;
  AVRational fr = stream->avg_frame_rate.num
                      ? stream->avg_frame_rate
                      : stream->r_frame_rate;
  idx->fps = fr.den ? av_q2d(fr) : 0.0;
  idx->num_samples = (int64_t)offsets.size();
  snprintf(idx->codec, sizeof(idx->codec), "%s",
           desc ? desc->name : "unknown");
  idx->tb_num = stream->time_base.num;
  idx->tb_den = stream->time_base.den;
  idx->sample_offsets = new uint64_t[offsets.size()];
  idx->sample_sizes = new uint64_t[sizes.size()];
  idx->sample_pts = new int64_t[pts.size()];
  idx->sample_dts = new int64_t[dts.size()];
  idx->keyflags = new uint8_t[keys.size()];
  memcpy(idx->sample_offsets, offsets.data(), offsets.size() * 8);
  memcpy(idx->sample_sizes, sizes.data(), sizes.size() * 8);
  memcpy(idx->sample_pts, pts.data(), pts.size() * 8);
  memcpy(idx->sample_dts, dts.data(), dts.size() * 8);
  memcpy(idx->keyflags, keys.data(), keys.size());
  if (par->extradata_size > 0) {
    idx->extradata = new uint8_t[par->extradata_size];
    memcpy(idx->extradata, par->extradata, par->extradata_size);
    idx->extradata_size = par->extradata_size;
  }
  avformat_close_input(&fmt);
  return idx;
}

// ---------------------------------------------------------------------------
// Decoder: exact-frame delivery from packet runs.
// ---------------------------------------------------------------------------

struct ScvidDecoder {
  AVCodecContext* ctx = nullptr;
  SwsContext* sws = nullptr;
  AVFrame* frame = nullptr;
  int width = 0;
  int height = 0;
  int sws_src_fmt = -1;  // source pixel format the sws context was built for
  int sws_for_fmt = -1;  // out_fmt the sws context was built for
  int sws_src_range = -1;  // source color range the sws context assumes
  // 0 = packed RGB24 (3 B/px, host-converted); 1 = planar YUV420 (I420,
  // 1.5 B/px) for pipelines that convert to RGB on the accelerator —
  // halving the host->device bytes is the point (the reference shipped
  // NV12 and converted on-GPU for the same reason, util/image.cu:22)
  int out_fmt = 0;
  int64_t emitted = 0;  // display-order frames emitted since last reset
  // over-aligned scratch surface for swscale output at widths whose
  // tight stride is not SIMD-safe (see convert_frame)
  std::vector<uint8_t> scratch;
};

SCVID_API ScvidDecoder* scvid_decoder_create(const char* codec_name,
                                             const uint8_t* extradata,
                                             int64_t extradata_size,
                                             int32_t width, int32_t height,
                                             int32_t n_threads) {
  const AVCodec* codec = avcodec_find_decoder_by_name(codec_name);
  if (!codec) {
    set_error(std::string("no decoder: ") + codec_name);
    return nullptr;
  }
  AVCodecContext* ctx = avcodec_alloc_context3(codec);
  if (extradata_size > 0) {
    ctx->extradata =
        (uint8_t*)av_mallocz(extradata_size + AV_INPUT_BUFFER_PADDING_SIZE);
    memcpy(ctx->extradata, extradata, extradata_size);
    ctx->extradata_size = (int)extradata_size;
  }
  ctx->width = width;
  ctx->height = height;
  ctx->thread_count = n_threads > 0 ? n_threads : 1;
  ctx->thread_type = FF_THREAD_FRAME | FF_THREAD_SLICE;
  int err = avcodec_open2(ctx, codec, nullptr);
  if (err < 0) {
    set_av_error("avcodec_open2", err);
    avcodec_free_context(&ctx);
    return nullptr;
  }
  ScvidDecoder* d = new ScvidDecoder();
  d->ctx = ctx;
  d->frame = av_frame_alloc();
  return d;
}

SCVID_API void scvid_decoder_destroy(ScvidDecoder* d) {
  if (!d) return;
  if (d->sws) sws_freeContext(d->sws);
  av_frame_free(&d->frame);
  avcodec_free_context(&d->ctx);
  delete d;
}

// Drop all buffered state; call on seek/discontinuity
// (reference decoder_automata.cpp discontinuity flush).
SCVID_API void scvid_decoder_reset(ScvidDecoder* d) {
  avcodec_flush_buffers(d->ctx);
  d->emitted = 0;
}

namespace {

// Output bytes per frame for the decoder's configured format.
int64_t frame_out_bytes(const ScvidDecoder* d, int64_t h, int64_t w) {
  if (d->out_fmt == 1) {
    int64_t ch = (h + 1) / 2, cw = (w + 1) / 2;
    return h * w + 2 * ch * cw;
  }
  return h * w * 3;
}

// (Re)build the cached sws context for the current frame -> dst_fmt.
int ensure_sws(ScvidDecoder* d, const AVFrame* f, AVPixelFormat dst_fmt) {
  int src_range = f->color_range == AVCOL_RANGE_JPEG ? 1 : 0;
  if (d->sws && d->width == f->width && d->height == f->height &&
      d->sws_src_fmt == f->format && d->sws_for_fmt == d->out_fmt &&
      d->sws_src_range == src_range)
    return 0;
  if (d->sws) sws_freeContext(d->sws);
  d->sws = sws_getContext(f->width, f->height, (AVPixelFormat)f->format,
                          f->width, f->height, dst_fmt, SWS_BILINEAR,
                          nullptr, nullptr, nullptr);
  d->width = f->width;
  d->height = f->height;
  d->sws_src_fmt = f->format;
  d->sws_for_fmt = d->out_fmt;
  d->sws_src_range = src_range;
  if (!d->sws) {
    set_error("sws_getContext failed");
    return -1;
  }
  if (src_range) {
    // Full range signaled via color_range on a non-J pixel format (e.g.
    // full-range HEVC decodes to yuv420p + AVCOL_RANGE_JPEG): swscale
    // infers ranges from the pixel formats alone, so tell it explicitly
    // — the I420 wire (and the RGB24 matrix) are limited-range.
    int *inv_table, *table, src_r, dst_r, b, c, s;
    if (sws_getColorspaceDetails(d->sws, &inv_table, &src_r, &table,
                                 &dst_r, &b, &c, &s) >= 0)
      sws_setColorspaceDetails(d->sws, inv_table, 1, table, 0, b, c, s);
  }
  return 0;
}

// Convert the decoder's current frame into dst:
//   out_fmt 0 — packed RGB24 (h*w*3 bytes, swscale)
//   out_fmt 1 — planar I420 (Y[h*w] U[ch*cw] V[ch*cw]); a straight
//               linesize-aware plane copy when the codec already decoded
//               LIMITED-RANGE 8-bit 4:2:0 (the overwhelmingly common
//               case for h264/hevc/mpeg4).  Full-range streams
//               (yuvj420p / color_range=JPEG, e.g. mjpeg) and uncommon
//               formats (10-bit, 4:2:2, ...) go through swscale, which
//               compresses to the limited range the on-device converter
//               (kernels/color.py, BT.601 studio swing) expects.
int convert_frame(ScvidDecoder* d, uint8_t* dst) {
  AVFrame* f = d->frame;
  const int64_t h = f->height, w = f->width;
  if (d->out_fmt == 1) {
    const int64_t ch = (h + 1) / 2, cw = (w + 1) / 2;
    uint8_t* dst_y = dst;
    uint8_t* dst_u = dst + h * w;
    uint8_t* dst_v = dst_u + ch * cw;
    if (f->format == AV_PIX_FMT_YUV420P &&
        f->color_range != AVCOL_RANGE_JPEG) {
      for (int64_t r = 0; r < h; ++r)
        memcpy(dst_y + r * w, f->data[0] + r * f->linesize[0], w);
      for (int64_t r = 0; r < ch; ++r) {
        memcpy(dst_u + r * cw, f->data[1] + r * f->linesize[1], cw);
        memcpy(dst_v + r * cw, f->data[2] + r * f->linesize[2], cw);
      }
      return 0;
    }
    if (ensure_sws(d, f, AV_PIX_FMT_YUV420P) < 0) return -1;
    if ((w % 32) == 0) {
      uint8_t* dst_planes[4] = {dst_y, dst_u, dst_v, nullptr};
      int dst_stride[4] = {(int)w, (int)cw, (int)cw, 0};
      sws_scale(d->sws, f->data, f->linesize, 0, h, dst_planes,
                dst_stride);
      return 0;
    }
    // Unaligned width: swscale's SIMD row writers store full vector
    // registers, overrunning a tight-packed destination row by up to
    // the vector width — at the last row that lands PAST the caller's
    // buffer (heap corruption for widths not a multiple of 16, found
    // in PR 9).  Scale into an over-aligned scratch surface and copy
    // tight rows out.
    const int ys = FFALIGN((int)w, 64), cs = FFALIGN((int)cw, 64);
    d->scratch.resize((size_t)ys * h + 2 * (size_t)cs * ch + 64);
    uint8_t* sy = d->scratch.data();
    uint8_t* su = sy + (size_t)ys * h;
    uint8_t* sv = su + (size_t)cs * ch;
    uint8_t* dst_planes[4] = {sy, su, sv, nullptr};
    int dst_stride[4] = {ys, cs, cs, 0};
    sws_scale(d->sws, f->data, f->linesize, 0, h, dst_planes, dst_stride);
    for (int64_t r = 0; r < h; ++r) memcpy(dst_y + r * w, sy + r * ys, w);
    for (int64_t r = 0; r < ch; ++r) {
      memcpy(dst_u + r * cw, su + r * cs, cw);
      memcpy(dst_v + r * cw, sv + r * cs, cw);
    }
    return 0;
  }
  if (ensure_sws(d, f, AV_PIX_FMT_RGB24) < 0) return -1;
  const int tight = 3 * (int)w;
  if ((w % 16) == 0) {
    uint8_t* dst_planes[4] = {dst, nullptr, nullptr, nullptr};
    int dst_stride[4] = {tight, 0, 0, 0};
    sws_scale(d->sws, f->data, f->linesize, 0, h, dst_planes,
              dst_stride);
    return 0;
  }
  // unaligned width: same SIMD-overrun hazard as above — aligned
  // scratch stride, then tight-row copy-out
  const int stride = FFALIGN(tight, 64);
  d->scratch.resize((size_t)stride * h + 64);
  uint8_t* dst_planes[4] = {d->scratch.data(), nullptr, nullptr, nullptr};
  int dst_stride[4] = {stride, 0, 0, 0};
  sws_scale(d->sws, f->data, f->linesize, 0, h, dst_planes, dst_stride);
  for (int64_t r = 0; r < h; ++r)
    memcpy(dst + r * tight, d->scratch.data() + r * stride, tight);
  return 0;
}

}  // namespace

// Select the decoder's output pixel layout: 0 = RGB24 (default),
// 1 = planar YUV420 (I420).  Takes effect for subsequent decode runs;
// callers size output buffers accordingly (h*w*3 vs h*w*3/2 rounded up).
SCVID_API void scvid_decoder_set_output_format(ScvidDecoder* d,
                                               int32_t fmt) {
  d->out_fmt = fmt == 1 ? 1 : 0;
}

// Decode a run of packets and write selected output frames.
//
//   packets      : concatenated packet payloads
//   pkt_sizes    : size of each packet, n_packets entries
//   wanted       : mask over output frames (display order, relative to the
//                  first frame this run emits *since the last reset*); may be
//                  shorter than the run's total output — excess frames drop.
//   n_wanted     : length of `wanted`
//   flush        : 1 = send EOF after the packets and drain the codec
//   out          : caller buffer of out_capacity bytes
//   out_capacity : size of `out`; decode aborts cleanly rather than overrun
//                  (guards against mid-stream geometry changes / stale index)
//   out_dims     : receives [height, width] of decoded frames
//
// Returns number of frames written, or -1 on error.  The decoder keeps
// counting emitted frames across calls until scvid_decoder_reset, so a long
// keyframe run can be streamed through multiple calls with a sliding mask.
SCVID_API int64_t scvid_decode_run(ScvidDecoder* d, const uint8_t* packets,
                                   const uint64_t* pkt_sizes,
                                   int64_t n_packets, const uint8_t* wanted,
                                   int64_t n_wanted, int32_t flush,
                                   uint8_t* out, int64_t out_capacity,
                                   int64_t* out_dims) {
  int64_t written = 0;
  int64_t frame_bytes = 0;
  AVPacket* pkt = av_packet_alloc();
  const uint8_t* cur = packets;

  auto drain = [&]() -> int {
    while (true) {
      int err = avcodec_receive_frame(d->ctx, d->frame);
      if (err == AVERROR(EAGAIN) || err == AVERROR_EOF) return 0;
      if (err < 0) {
        set_av_error("receive_frame", err);
        return -1;
      }
      if (frame_bytes == 0) {
        out_dims[0] = d->frame->height;
        out_dims[1] = d->frame->width;
        frame_bytes = frame_out_bytes(d, d->frame->height, d->frame->width);
      } else if (d->frame->height != out_dims[0] ||
                 d->frame->width != out_dims[1]) {
        // mid-stream geometry change (new SPS): frames of differing size
        // can't be packed into the caller's uniform array — writing one at
        // an offset computed with the old frame_bytes would overrun.
        set_error("frame geometry changed mid-run (mid-stream SPS change?)");
        return -1;
      }
      int64_t fi = d->emitted++;
      if (fi < n_wanted && wanted[fi]) {
        if ((written + 1) * frame_bytes > out_capacity) {
          set_error("decode output exceeds buffer capacity (geometry "
                    "mismatch with index?)");
          return -1;
        }
        if (convert_frame(d, out + written * frame_bytes) < 0) return -1;
        written++;
      }
      av_frame_unref(d->frame);
    }
  };

  for (int64_t i = 0; i < n_packets; ++i) {
    av_packet_unref(pkt);
    // const-cast is safe: we set pkt as a read-only view for send_packet
    pkt->data = const_cast<uint8_t*>(cur);
    pkt->size = (int)pkt_sizes[i];
    cur += pkt_sizes[i];
    int err;
    while ((err = avcodec_send_packet(d->ctx, pkt)) == AVERROR(EAGAIN)) {
      // codec input queue full: drain output, then resend this packet
      if (drain() < 0) {
        av_packet_free(&pkt);
        return -1;
      }
    }
    if (err < 0) {
      // Corrupt packet: report, don't crash the pipeline
      set_av_error("send_packet", err);
      av_packet_free(&pkt);
      return -1;
    }
    if (drain() < 0) {
      av_packet_free(&pkt);
      return -1;
    }
  }
  if (flush) {
    avcodec_send_packet(d->ctx, nullptr);
    if (drain() < 0) {
      av_packet_free(&pkt);
      return -1;
    }
    avcodec_flush_buffers(d->ctx);
  }
  av_packet_free(&pkt);
  return written;
}

SCVID_API int64_t scvid_decoder_emitted(ScvidDecoder* d) { return d->emitted; }

// Resumable pts-matched decode with a HARD frame budget: stops (instead
// of erroring) when `max_frames` matched frames have been written, and
// reports how many packets were consumed so the caller re-feeds the
// remainder on the next call.  This is the primitive behind chunked
// work-packet streaming: a bounded output buffer (a work packet, not a
// packet run + reorder-margin) regardless of codec delay.  Unlike
// scvid_decode_run_pts, the codec is NOT flushed/reset at the end —
// call scvid_decoder_reset when the logical run is abandoned.
//
//   flush=1 + all packets consumed: EOF is sent and the tail drained
//   (a repeated EOF send from a resumed call is tolerated).
//   Returns frames written, or -1 on error; *consumed = packets fed.
//   No progress (written==0 && *consumed==0) on a flush call means the
//   stream is drained dry — any undelivered wanted frames will never
//   come (caller retries from an earlier keyframe or reports).
SCVID_API int64_t scvid_decode_run_pts_stream(
    ScvidDecoder* d, const uint8_t* packets, const uint64_t* pkt_sizes,
    const int64_t* pkt_pts, int64_t n_packets, const int64_t* wanted_pts,
    int64_t n_wanted, uint8_t* deliv, int32_t flush, int64_t max_frames,
    uint8_t* out, int64_t out_capacity, int64_t* out_dims,
    int64_t* consumed) {
  int64_t written = 0;
  int64_t cursor = 0;
  int64_t frame_bytes = 0;
  AVPacket* pkt = av_packet_alloc();
  const uint8_t* cur = packets;
  memset(deliv, 0, (size_t)n_wanted);
  *consumed = 0;

  // 0 = drained (EAGAIN/EOF), 1 = budget reached, -1 = error
  auto drain = [&]() -> int {
    while (true) {
      if (written >= max_frames) return 1;
      int err = avcodec_receive_frame(d->ctx, d->frame);
      if (err == AVERROR(EAGAIN) || err == AVERROR_EOF) return 0;
      if (err < 0) {
        set_av_error("receive_frame", err);
        return -1;
      }
      if (frame_bytes == 0) {
        out_dims[0] = d->frame->height;
        out_dims[1] = d->frame->width;
        frame_bytes = frame_out_bytes(d, d->frame->height, d->frame->width);
      } else if (d->frame->height != out_dims[0] ||
                 d->frame->width != out_dims[1]) {
        set_error("frame geometry changed mid-run (mid-stream SPS change?)");
        return -1;
      }
      d->emitted++;
      int64_t fpts = d->frame->best_effort_timestamp != AV_NOPTS_VALUE
                         ? d->frame->best_effort_timestamp
                         : d->frame->pts;
      while (cursor < n_wanted && wanted_pts[cursor] < fpts) cursor++;
      if (cursor < n_wanted && wanted_pts[cursor] == fpts) {
        if ((written + 1) * frame_bytes > out_capacity) {
          set_error("decode output exceeds buffer capacity (geometry "
                    "mismatch with index?)");
          return -1;
        }
        if (convert_frame(d, out + written * frame_bytes) < 0) return -1;
        deliv[cursor] = 1;
        cursor++;
        written++;
      }
      av_frame_unref(d->frame);
    }
  };

  // resume: harvest frames the codec already holds from earlier calls
  int dr = drain();
  if (dr < 0) {
    av_packet_free(&pkt);
    return -1;
  }
  for (int64_t i = 0; dr == 0 && i < n_packets; ++i) {
    av_packet_unref(pkt);
    pkt->data = const_cast<uint8_t*>(cur);
    pkt->size = (int)pkt_sizes[i];
    pkt->pts = pkt_pts[i];
    int err;
    while ((err = avcodec_send_packet(d->ctx, pkt)) == AVERROR(EAGAIN)) {
      dr = drain();
      if (dr != 0) break;
    }
    if (dr != 0) break;  // budget reached mid-EAGAIN: packet NOT consumed
    if (err < 0) {
      set_av_error("send_packet", err);
      av_packet_free(&pkt);
      return -1;
    }
    cur += pkt_sizes[i];
    (*consumed)++;
    dr = drain();
  }
  if (dr == 0 && flush && *consumed == n_packets) {
    int err = avcodec_send_packet(d->ctx, nullptr);
    // a resumed flush call re-sends EOF: AVERROR_EOF is expected then
    if (err < 0 && err != AVERROR_EOF) {
      set_av_error("send_packet(EOF)", err);
      av_packet_free(&pkt);
      return -1;
    }
    dr = drain();
  }
  av_packet_free(&pkt);
  return dr < 0 ? -1 : written;
}

// Pts-matched variant of scvid_decode_run: packets carry their container
// pts, and frames are selected by timestamp membership instead of emission
// position.  This stays exact on streams where positional masks break:
//   - open-GOP seeks, where the decoder may emit (or drop) leading frames
//     whose references precede the seek keyframe;
//   - VFR streams, where display position is defined by pts order alone.
//
//   pkt_pts     : pts per packet, n_packets entries (decode order)
//   wanted_pts  : sorted ascending, unique; frames are emitted in pts order
//                 so a single forward cursor matches them
//   deliv       : uint8 per wanted entry, set to 1 when that pts is written
//
// Output frames are packed in delivery (ascending-pts) order.  Returns the
// number written (<= n_wanted), or -1 on error.  Missing timestamps are NOT
// an error here — the caller inspects `deliv` and replans (e.g. restart
// from an earlier keyframe for open-GOP leading frames).
SCVID_API int64_t scvid_decode_run_pts(
    ScvidDecoder* d, const uint8_t* packets, const uint64_t* pkt_sizes,
    const int64_t* pkt_pts, int64_t n_packets, const int64_t* wanted_pts,
    int64_t n_wanted, uint8_t* deliv, int32_t flush, uint8_t* out,
    int64_t out_capacity, int64_t* out_dims) {
  // one-shot = the resumable stream primitive with an unbounded frame
  // budget, plus the codec flush/reset the streaming caller defers
  int64_t consumed = 0;
  int64_t n = scvid_decode_run_pts_stream(
      d, packets, pkt_sizes, pkt_pts, n_packets, wanted_pts, n_wanted,
      deliv, flush, INT64_MAX, out, out_capacity, out_dims, &consumed);
  if (n < 0) return -1;
  if (flush) avcodec_flush_buffers(d->ctx);
  return n;
}

// ---------------------------------------------------------------------------
// Encoder: RGB24 frames -> H.264 (or any libavcodec encoder) packets.
// ---------------------------------------------------------------------------

struct ScvidEncoder {
  AVCodecContext* ctx = nullptr;
  SwsContext* sws = nullptr;
  AVFrame* frame = nullptr;
  AVPacket* pkt = nullptr;
  int64_t pts = 0;
  // drained packets waiting for pickup
  std::vector<std::vector<uint8_t>> out_packets;
  std::vector<uint8_t> out_keys;
  std::vector<int64_t> out_pts;
  std::vector<int64_t> out_dts;
  // over-aligned scratch for the RGB24 SOURCE surface at widths whose
  // tight stride is not SIMD-safe (see scvid_encoder_feed_pts — the
  // read-side sibling of the decoder's convert_frame hazard)
  std::vector<uint8_t> scratch;
};

SCVID_API ScvidEncoder* scvid_encoder_create(int32_t width, int32_t height,
                                             int32_t fps_num, int32_t fps_den,
                                             const char* codec_name,
                                             int64_t bitrate, int32_t crf,
                                             int32_t keyint,
                                             int32_t bframes,
                                             int32_t open_gop) {
  const AVCodec* codec = avcodec_find_encoder_by_name(codec_name);
  if (!codec) {
    set_error(std::string("no encoder: ") + codec_name);
    return nullptr;
  }
  AVCodecContext* ctx = avcodec_alloc_context3(codec);
  ctx->width = width;
  ctx->height = height;
  ctx->time_base = {fps_den, fps_num};
  ctx->framerate = {fps_num, fps_den};
  // pick the first 8-bit 4:2:0 format in the codec's own preference
  // order: yuv420p for x264/x265/mpeg4, yuvj420p for mjpeg (which lists
  // yuv420p too but rejects limited range at default strictness); fall
  // back to the codec's first advertised format
  AVPixelFormat enc_fmt = AV_PIX_FMT_YUV420P;
  if (codec->pix_fmts) {
    enc_fmt = codec->pix_fmts[0];
    for (const AVPixelFormat* p = codec->pix_fmts;
         *p != AV_PIX_FMT_NONE; ++p)
      if (*p == AV_PIX_FMT_YUV420P || *p == AV_PIX_FMT_YUVJ420P) {
        enc_fmt = *p;
        break;
      }
  }
  ctx->pix_fmt = enc_fmt;
  if (enc_fmt == AV_PIX_FMT_YUVJ420P || enc_fmt == AV_PIX_FMT_YUVJ422P ||
      enc_fmt == AV_PIX_FMT_YUVJ444P)
    ctx->color_range = AVCOL_RANGE_JPEG;
  ctx->gop_size = keyint > 0 ? keyint : 16;
  // bframes=0 (the sink default) keeps exact-seek trivial on our own
  // outputs; >0 produces pts!=dts reordered streams — how real-world
  // mp4s look, and what the decode-index tests exercise
  ctx->max_b_frames = bframes > 0 ? bframes : 0;
  // SPS/PPS in extradata, not per-keyframe (matches mp4-style storage)
  ctx->flags |= AV_CODEC_FLAG_GLOBAL_HEADER;
  if (bitrate > 0) ctx->bit_rate = bitrate;
  if (strcmp(codec_name, "libx264") == 0) {
    av_opt_set(ctx->priv_data, "preset", "veryfast", 0);
    if (bitrate <= 0)
      av_opt_set_int(ctx->priv_data, "crf", crf > 0 ? crf : 20, 0);
    std::string params;
    if (bframes > 0) {
      // fixed B pattern (b-adapt=0, no scenecut): the knob exists to
      // produce reordered (pts != dts) streams deterministically;
      // x264's adaptive strategy / scenecut would silently emit
      // all-I/P for simple content
      params = "b-adapt=0:scenecut=0";
    }
    if (open_gop) {
      // non-IDR recovery points: GOP-boundary I frames whose leading B
      // frames reference the previous GOP — the stream shape that makes
      // positional seek masks unsafe (the pts-matched decode path covers
      // it; tests build such fixtures through this knob)
      if (!params.empty()) params += ":";
      params += "open-gop=1";
    }
    if (!params.empty())
      av_opt_set(ctx->priv_data, "x264-params", params.c_str(), 0);
  } else if (strcmp(codec_name, "libx265") == 0) {
    av_opt_set(ctx->priv_data, "preset", "veryfast", 0);
    // mirror the x264 knob semantics so fixtures behave the same across
    // codecs: crf honored, open_gop explicit, deterministic B pattern
    std::string params = "log-level=error";
    if (bitrate <= 0)
      params += ":crf=" + std::to_string(crf > 0 ? crf : 23);
    params += open_gop ? ":open-gop=1" : ":open-gop=0";
    if (bframes > 0) params += ":b-adapt=0:scenecut=0";
    av_opt_set(ctx->priv_data, "x265-params", params.c_str(), 0);
  }
  int err = avcodec_open2(ctx, codec, nullptr);
  if (err < 0) {
    set_av_error("encoder open", err);
    avcodec_free_context(&ctx);
    return nullptr;
  }
  ScvidEncoder* e = new ScvidEncoder();
  e->ctx = ctx;
  e->frame = av_frame_alloc();
  e->frame->format = enc_fmt;
  e->frame->width = width;
  e->frame->height = height;
  av_frame_get_buffer(e->frame, 0);
  e->pkt = av_packet_alloc();
  e->sws = sws_getContext(width, height, AV_PIX_FMT_RGB24, width, height,
                          enc_fmt, SWS_BILINEAR, nullptr, nullptr,
                          nullptr);
  return e;
}

SCVID_API void scvid_encoder_destroy(ScvidEncoder* e) {
  if (!e) return;
  if (e->sws) sws_freeContext(e->sws);
  av_frame_free(&e->frame);
  av_packet_free(&e->pkt);
  avcodec_free_context(&e->ctx);
  delete e;
}

SCVID_API int64_t scvid_encoder_extradata(ScvidEncoder* e, uint8_t* buf,
                                          int64_t bufsize) {
  if (!e->ctx->extradata) return 0;
  if (buf && bufsize >= e->ctx->extradata_size)
    memcpy(buf, e->ctx->extradata, e->ctx->extradata_size);
  return e->ctx->extradata_size;
}

// The container-level codec descriptor of this encoder's output ("h264",
// "hevc", ...) — the authoritative name for scvid_mp4_write / the ingest
// index, so callers never maintain an encoder-name -> descriptor map.
SCVID_API const char* scvid_encoder_descriptor(ScvidEncoder* e) {
  const AVCodecDescriptor* d = avcodec_descriptor_get(e->ctx->codec_id);
  return d ? d->name : "";
}

namespace {

int encoder_drain(ScvidEncoder* e) {
  while (true) {
    int err = avcodec_receive_packet(e->ctx, e->pkt);
    if (err == AVERROR(EAGAIN) || err == AVERROR_EOF) return 0;
    if (err < 0) {
      set_av_error("receive_packet", err);
      return -1;
    }
    e->out_packets.emplace_back(e->pkt->data, e->pkt->data + e->pkt->size);
    e->out_keys.push_back((e->pkt->flags & AV_PKT_FLAG_KEY) ? 1 : 0);
    e->out_pts.push_back(e->pkt->pts);
    e->out_dts.push_back(e->pkt->dts);
    av_packet_unref(e->pkt);
  }
}

}  // namespace

// Feed n RGB24 frames (contiguous, h*w*3 each). Returns 0 / -1.
// `pts` (optional, may be NULL): per-frame presentation timestamps in the
// encoder time base — strictly increasing; enables VFR streams.  NULL
// keeps the default sequential numbering.
SCVID_API int32_t scvid_encoder_feed_pts(ScvidEncoder* e, const uint8_t* rgb,
                                         int64_t n_frames,
                                         const int64_t* pts) {
  const int w = e->ctx->width, h = e->ctx->height;
  const int tight = 3 * w;
  for (int64_t i = 0; i < n_frames; ++i) {
    av_frame_make_writable(e->frame);
    const uint8_t* src = rgb + (size_t)i * tight * h;
    const uint8_t* src_planes[4] = {src, nullptr, nullptr, nullptr};
    int src_stride[4] = {tight, 0, 0, 0};
    if ((w % 16) != 0) {
      // Unaligned width: swscale's SIMD row READERS load full vector
      // registers past the tight row end — at the last row of the
      // caller's packed buffer that read lands PAST the allocation
      // (the read-side sibling of the decoder convert_frame overrun
      // fixed in PR 9).  Stage the frame into an over-aligned scratch
      // source and feed swscale from that.
      const int stride = FFALIGN(tight, 64);
      e->scratch.resize((size_t)stride * h + 64);
      for (int64_t r = 0; r < h; ++r)
        memcpy(e->scratch.data() + (size_t)r * stride, src + r * tight,
               tight);
      src_planes[0] = e->scratch.data();
      src_stride[0] = stride;
    }
    sws_scale(e->sws, src_planes, src_stride, 0, h,
              e->frame->data, e->frame->linesize);
    if (pts) {
      if (pts[i] < e->pts) {
        set_error("feed_pts: timestamps must be strictly increasing");
        return -1;
      }
      e->frame->pts = pts[i];
      e->pts = pts[i] + 1;
    } else {
      e->frame->pts = e->pts++;
    }
    int err = avcodec_send_frame(e->ctx, e->frame);
    if (err < 0) {
      set_av_error("send_frame", err);
      return -1;
    }
    if (encoder_drain(e) < 0) return -1;
  }
  return 0;
}

SCVID_API int32_t scvid_encoder_feed(ScvidEncoder* e, const uint8_t* rgb,
                                     int64_t n_frames) {
  return scvid_encoder_feed_pts(e, rgb, n_frames, nullptr);
}

SCVID_API int32_t scvid_encoder_flush(ScvidEncoder* e) {
  int err = avcodec_send_frame(e->ctx, nullptr);
  if (err < 0 && err != AVERROR_EOF) {
    set_av_error("flush", err);
    return -1;
  }
  return encoder_drain(e);
}

// Packet pickup: sizes first, then payload copy-out; clears the queue.
SCVID_API int64_t scvid_encoder_pending(ScvidEncoder* e) {
  return (int64_t)e->out_packets.size();
}

SCVID_API int64_t scvid_encoder_pending_bytes(ScvidEncoder* e) {
  int64_t total = 0;
  for (auto& p : e->out_packets) total += (int64_t)p.size();
  return total;
}

SCVID_API void scvid_encoder_take(ScvidEncoder* e, uint8_t* data,
                                  uint64_t* sizes, uint8_t* keys,
                                  int64_t* pts, int64_t* dts) {
  uint64_t off = 0;
  for (size_t i = 0; i < e->out_packets.size(); ++i) {
    auto& p = e->out_packets[i];
    memcpy(data + off, p.data(), p.size());
    sizes[i] = p.size();
    keys[i] = e->out_keys[i];
    pts[i] = e->out_pts[i];
    dts[i] = e->out_dts[i];
    off += p.size();
  }
  e->out_packets.clear();
  e->out_keys.clear();
  e->out_pts.clear();
  e->out_dts.clear();
}

// ---------------------------------------------------------------------------
// MP4 export (reference storage.py:365 save_mp4)
// ---------------------------------------------------------------------------

// pts/dts are expressed in time base tb_num/tb_den (pass 1/fps_num-style
// frame numbering as tb = fps_den/fps_num).
SCVID_API int32_t scvid_mp4_write(const char* path, int32_t width,
                                  int32_t height, int32_t fps_num,
                                  int32_t fps_den, int32_t tb_num,
                                  int32_t tb_den, const char* codec_name,
                                  const uint8_t* extradata,
                                  int64_t extradata_size,
                                  const uint8_t* packets,
                                  const uint64_t* pkt_sizes,
                                  const uint8_t* keys, const int64_t* pts,
                                  const int64_t* dts, int64_t n_packets) {
  AVFormatContext* fmt = nullptr;
  int err = avformat_alloc_output_context2(&fmt, nullptr, "mp4", path);
  if (err < 0 || !fmt) {
    set_av_error("alloc mp4 muxer", err);
    return -1;
  }
  const AVCodecDescriptor* desc = avcodec_descriptor_get_by_name(codec_name);
  AVStream* stream = avformat_new_stream(fmt, nullptr);
  stream->codecpar->codec_type = AVMEDIA_TYPE_VIDEO;
  stream->codecpar->codec_id = desc ? desc->id : AV_CODEC_ID_H264;
  stream->codecpar->width = width;
  stream->codecpar->height = height;
  if (extradata_size > 0) {
    stream->codecpar->extradata = (uint8_t*)av_mallocz(
        extradata_size + AV_INPUT_BUFFER_PADDING_SIZE);
    memcpy(stream->codecpar->extradata, extradata, extradata_size);
    stream->codecpar->extradata_size = (int)extradata_size;
  }
  stream->time_base = {fps_den, fps_num};
  err = avio_open(&fmt->pb, path, AVIO_FLAG_WRITE);
  if (err < 0) {
    set_av_error("avio_open", err);
    avformat_free_context(fmt);
    return -1;
  }
  err = avformat_write_header(fmt, nullptr);
  if (err < 0) {
    set_av_error("write_header", err);
    avio_closep(&fmt->pb);
    avformat_free_context(fmt);
    return -1;
  }
  AVPacket* pkt = av_packet_alloc();
  const uint8_t* cur = packets;
  for (int64_t i = 0; i < n_packets; ++i) {
    pkt->data = const_cast<uint8_t*>(cur);
    pkt->size = (int)pkt_sizes[i];
    pkt->pts = av_rescale_q(pts[i], {tb_num, tb_den}, stream->time_base);
    pkt->dts = av_rescale_q(dts[i], {tb_num, tb_den}, stream->time_base);
    // Every packet needs a duration: without it the final sample gets
    // stts delta 0, the track/edit-list duration excludes the last frame
    // period, and (depending on ms rounding of the edit list) demuxers
    // drop the final frame and misreport avg_frame_rate.
    int64_t next = (i + 1 < n_packets)
                       ? av_rescale_q(dts[i + 1], {tb_num, tb_den},
                                      stream->time_base)
                       : 0;
    pkt->duration = (i + 1 < n_packets)
                        ? next - pkt->dts
                        : av_rescale_q(1, {fps_den, fps_num},
                                       stream->time_base);
    pkt->flags = keys[i] ? AV_PKT_FLAG_KEY : 0;
    pkt->stream_index = 0;
    cur += pkt_sizes[i];
    err = av_interleaved_write_frame(fmt, pkt);
    if (err < 0) {
      set_av_error("write_frame", err);
      av_packet_free(&pkt);
      avio_closep(&fmt->pb);
      avformat_free_context(fmt);
      return -1;
    }
  }
  av_packet_free(&pkt);
  av_write_trailer(fmt);
  avio_closep(&fmt->pb);
  avformat_free_context(fmt);
  return 0;
}
